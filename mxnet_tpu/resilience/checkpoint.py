"""Crash-atomic step checkpoints: tmp-dir write + rename commit.

The orbax-backed :mod:`mxnet_tpu.utils.checkpoint` is the pod-scale
async path; this module is the *resilience* path — a synchronous,
self-contained format whose commit point is a single ``os.rename`` of a
fully written temp directory, so a kill at ANY instant of a save leaves
either the previous committed checkpoint or the new one, never a torn
"latest":

1. leaves are serialized into ``<dir>/.tmp-<step>-<pid>/state.mxtpu``
   (the dmlc-container-parity format of
   :mod:`mxnet_tpu.utils.serialization`, itself written atomically) plus
   a small ``meta.json``;
2. the temp dir is renamed to ``<dir>/step-<NNNNNNNN>`` — POSIX-atomic;
   the injection site ``"checkpoint.commit"`` sits right before this
   rename, so chaos tests can kill mid-save and prove nothing corrupts;
3. ``latest_step()`` only ever sees fully renamed directories; stale
   ``.tmp-*`` dirs from killed saves are swept on construction.

There is deliberately NO separate "latest" marker file: the set of
committed directories IS the source of truth, so no ordering bug between
"write data" and "write marker" can exist.

Atomicity alone is trust-on-read: the rename proves a save COMPLETED,
not that the bytes on disk today are the bytes committed then.  So every
save also writes a ``MANIFEST.json`` (per-file BLAKE2b digest + size,
:mod:`.integrity`) inside the tmp dir *before* the commit rename — the
manifest is atomic with the data — and ``restore`` verifies digests
before deserializing.  A corrupt/torn/missing step is QUARANTINED
(renamed ``corrupt-<step>``, never deleted) and restore falls back down
the chain to the newest intact step, raising the typed
:class:`~.integrity.CheckpointCorruptError` only when no intact step
exists.  ``_gc`` verifies-or-skips: it never deletes the newest intact
step (or the last step a restore verified), so a commit whose bytes rot
immediately after the rename — the ``"checkpoint.corrupt"`` fault site
simulates exactly this — can no longer take every restorable fallback
with it.  See docs/integrity.md.
"""
from __future__ import annotations

import json
import os
import shutil
from typing import Any, Dict, List, Optional, Tuple

from ..base import MXNetError
from ..observability.trace import active as _trace_active
from .faults import inject, poison
from .integrity import (CheckpointCorruptError, TreeHasher,
                        _count_registry, _warn_legacy_once, flip_bytes,
                        verify_step_dir, write_manifest,
                        MANIFEST_SCHEMA_VERSION)

__all__ = ["AtomicCheckpointer", "CheckpointCorruptError"]

_STEP_PREFIX = "step-"
_TMP_PREFIX = ".tmp-"
_CORRUPT_PREFIX = "corrupt-"
_STATE_FILE = "state.mxtpu"
_META_FILE = "meta.json"


class AtomicCheckpointer:
    """Commit-or-nothing step checkpoints under one directory.

    ``save(step, tree)`` takes a flat ``{name: NDArray}`` dict (see
    ``ShardedTrainer.state_dict()``); ``restore(step=None)`` returns
    ``(tree, meta)`` for the requested or latest committed step.
    ``max_to_keep`` garbage-collects oldest committed steps AFTER each
    successful commit (never before — a failed save must not eat the
    fallback).
    """

    def __init__(self, directory: str, max_to_keep: Optional[int] = None):
        self.directory = os.path.abspath(str(directory))
        self.max_to_keep = max_to_keep
        # the newest step a restore() actually verified + deserialized:
        # _gc never collects it, whatever max_to_keep says
        self._last_verified: Optional[int] = None
        os.makedirs(self.directory, exist_ok=True)
        self._sweep_tmp()

    # ----------------------------------------------------------- inventory
    def _sweep_tmp(self):
        for name in os.listdir(self.directory):
            if not name.startswith(_TMP_PREFIX):
                continue
            path = os.path.join(self.directory, name)
            if name.startswith(_TMP_PREFIX + "old-"):
                # a re-commit moved a COMMITTED step aside and was killed
                # before finishing: if the step dir is gone, the aside
                # copy is the only committed state — recover it
                try:
                    step = int(name[len(_TMP_PREFIX + "old-"):].split("-")[0])
                except ValueError:
                    step = None
                if step is not None and not os.path.isdir(
                        self._step_dir(step)):
                    os.rename(path, self._step_dir(step))
                    continue
            shutil.rmtree(path, ignore_errors=True)

    def all_steps(self) -> List[int]:
        out = []
        for name in os.listdir(self.directory):
            if name.startswith(_STEP_PREFIX):
                try:
                    out.append(int(name[len(_STEP_PREFIX):]))
                except ValueError:
                    continue
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def quarantined(self) -> List[str]:
        """Names of quarantined (``corrupt-*``) directories — kept for
        forensics, never restored from, never GC'd."""
        return sorted(name for name in os.listdir(self.directory)
                      if name.startswith(_CORRUPT_PREFIX)
                      and os.path.isdir(os.path.join(self.directory, name)))

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"{_STEP_PREFIX}{step:08d}")

    # ---------------------------------------------------------------- save
    def save(self, step: int, tree: Dict[str, Any],
             meta: Optional[dict] = None) -> str:
        """Write and atomically commit one step.  Returns the committed
        path.  Re-committing an existing step replaces it (the
        resume-replays-a-step case; earlier steps stay as fallback)."""
        inject("checkpoint.save")
        tr = _trace_active()
        if tr is None:
            return self._save(step, tree, meta)
        # context-managed like every other site, so a failed save tags
        # its span with error=<type> instead of looking clean
        with tr.span("checkpoint.save", step=int(step)):
            return self._save(step, tree, meta)

    def _save(self, step: int, tree: Dict[str, Any],
              meta: Optional[dict]) -> str:
        from ..utils.serialization import save as _save

        step = int(step)
        tmp = os.path.join(self.directory,
                           f"{_TMP_PREFIX}{step:08d}-{os.getpid()}")
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        # tee-digest the state file in the same pass that writes it —
        # the manifest records exactly the bytes that went through the
        # writer, with no re-read between write and digest
        hasher = TreeHasher()
        _save(os.path.join(tmp, _STATE_FILE), dict(tree), tee=hasher)
        with open(os.path.join(tmp, _META_FILE), "w") as f:
            # the integrity stamp lets verify tell a DELETED manifest
            # (corrupt) from a pre-manifest legacy checkpoint; stamped
            # AFTER the caller's meta so a round-tripped meta dict can
            # never mask the reserved step/integrity keys
            doc = dict(meta or {})
            doc["step"] = step
            doc["integrity"] = MANIFEST_SCHEMA_VERSION
            json.dump(doc, f)
        # manifest INSIDE the tmp dir, before the commit rename: the
        # digests are atomic with the data they describe
        write_manifest(tmp, precomputed={_STATE_FILE: hasher.hexdigest()})
        inject("checkpoint.commit")
        final = self._step_dir(step)
        aside = None
        if os.path.exists(final):
            # re-committing an existing step: move the old dir ASIDE
            # (rename, not delete) so a kill between here and the commit
            # rename still leaves one committed copy of this step —
            # .old- dirs are swept with the tmp dirs on construction
            aside = os.path.join(self.directory,
                                 f"{_TMP_PREFIX}old-{step:08d}-{os.getpid()}")
            shutil.rmtree(aside, ignore_errors=True)
            os.rename(final, aside)
        try:
            os.rename(tmp, final)      # THE commit point
        except BaseException:
            if aside is not None and not os.path.exists(final):
                os.rename(aside, final)    # roll the old commit back in
                aside = None
            raise
        if aside is not None:
            shutil.rmtree(aside, ignore_errors=True)
        if poison("checkpoint.corrupt") is not None:
            # chaos: post-commit bit rot on the committed state file —
            # fires BEFORE _gc so the verify-or-skip GC contract is
            # exercised on exactly the save that rotted
            flip_bytes(os.path.join(final, _STATE_FILE))
        self._gc()
        # fleet counter for DIRECT checkpointer users; ResilientLoop
        # additionally counts its own commits into stats()["resilience"]
        try:
            from ..observability.registry import default_registry
            default_registry().counter(
                "mxtpu_checkpoint_commits_total",
                help="atomic checkpoint commits (rename succeeded)").inc()
        except Exception:
            pass
        return final

    def _gc(self):
        """Collect oldest committed steps beyond ``max_to_keep`` —
        verify-or-skip: quarantined dirs are invisible here (they left
        the ``step-`` namespace), and at least one INTACT step always
        survives.  The old blind version could delete every fallback
        right after a commit whose bytes were already corrupt on disk,
        leaving zero restorable state."""
        if self.max_to_keep is None:
            return
        steps = self.all_steps()
        excess = steps[:max(0, len(steps) - self.max_to_keep)]
        if not excess:
            return
        newest_intact = None
        for s in reversed(steps):
            status, _why = verify_step_dir(self._step_dir(s), _META_FILE)
            if status != "corrupt":          # legacy counts as restorable
                newest_intact = s
                break
        if newest_intact is None:
            # every step is corrupt: delete NOTHING — the dirs are
            # evidence, and restore() will quarantine + raise typed
            return
        keep = {newest_intact, self._last_verified}
        for s in excess:
            if s in keep:
                continue
            shutil.rmtree(self._step_dir(s), ignore_errors=True)

    # ------------------------------------------------------------- restore
    def _quarantine(self, step: int, reason: str) -> str:
        """Move a corrupt step dir aside as ``corrupt-<step>`` (suffixed
        for uniqueness if the step rots more than once) — NEVER deleted:
        the bytes are the only forensic evidence of what went wrong."""
        src = self._step_dir(step)
        dst = os.path.join(self.directory, f"{_CORRUPT_PREFIX}{step:08d}")
        n = 1
        while os.path.exists(dst):
            n += 1
            dst = os.path.join(self.directory,
                               f"{_CORRUPT_PREFIX}{step:08d}-{n}")
        os.rename(src, dst)
        try:
            with open(os.path.join(dst, "QUARANTINE.txt"), "w") as f:
                f.write(reason + "\n")
        except OSError:
            pass                   # evidence preservation is best-effort
        _count_registry("mxtpu_checkpoint_quarantined_total",
                        help="corrupt checkpoint step dirs quarantined "
                             "(renamed corrupt-<step>, kept on disk)")
        return dst

    def restore(self, step: Optional[int] = None) \
            -> Tuple[Dict[str, Any], dict]:
        """Verified restore of the requested (or latest) step.

        Each candidate is digest-verified BEFORE deserialization; a
        corrupt/torn/missing-file step is quarantined and restore falls
        back to the next-older step — so the returned ``meta["step"]``
        may be older than asked, and callers resuming training replay
        from it (``ResilientLoop`` already keys its replay off the
        meta).  Manifest-less legacy steps restore with a one-time
        warning.  Raises :class:`CheckpointCorruptError` (carrying the
        steps this call quarantined) only when no intact step remains;
        asking for a step that never existed keeps raising the plain
        ``MXNetError``.
        """
        from ..utils.serialization import load as _load

        inject("checkpoint.restore")
        steps = self.all_steps()
        if step is None:
            if not steps:
                raise MXNetError(
                    f"no checkpoint found under {self.directory} "
                    f"(all_steps={self.all_steps()})")
            candidates = steps[::-1]
        else:
            step = int(step)
            if not os.path.isdir(self._step_dir(step)):
                raise MXNetError(
                    f"no checkpoint for step {step} under "
                    f"{self.directory} (all_steps={self.all_steps()})")
            candidates = [s for s in steps if s <= step][::-1]
        quarantined: List[int] = []
        for s in candidates:
            path = self._step_dir(s)
            status, why = verify_step_dir(path, _META_FILE)
            if status == "corrupt":
                self._quarantine(s, why or "verification failed")
                quarantined.append(s)
                continue
            if status == "legacy":
                _warn_legacy_once(path)
            try:
                tree = _load(os.path.join(path, _STATE_FILE))
                with open(os.path.join(path, _META_FILE)) as f:
                    meta = json.load(f)
            except Exception as e:
                # digests matched (or legacy had none) yet the payload
                # would not deserialize — same failure class, same
                # response.  BaseException (SimulatedPreemption, ^C)
                # still propagates: a kill is not corruption.
                self._quarantine(s, f"deserialize failed: {e!r}")
                quarantined.append(s)
                continue
            self._last_verified = s
            return tree, meta
        raise CheckpointCorruptError(
            f"no intact checkpoint under {self.directory}: "
            f"{len(quarantined)} step(s) quarantined this call "
            f"({quarantined}, newest first); corrupt-* dirs kept for "
            "forensics", quarantined=quarantined)

    def __repr__(self):
        return (f"AtomicCheckpointer({self.directory!r}, "
                f"steps={self.all_steps()})")
