"""Crash-atomic step checkpoints: tmp-dir write + rename commit.

The orbax-backed :mod:`mxnet_tpu.utils.checkpoint` is the pod-scale
async path; this module is the *resilience* path — a synchronous,
self-contained format whose commit point is a single ``os.rename`` of a
fully written temp directory, so a kill at ANY instant of a save leaves
either the previous committed checkpoint or the new one, never a torn
"latest":

1. leaves are serialized into ``<dir>/.tmp-<step>-<pid>/state.mxtpu``
   (the dmlc-container-parity format of
   :mod:`mxnet_tpu.utils.serialization`, itself written atomically) plus
   a small ``meta.json``;
2. the temp dir is renamed to ``<dir>/step-<NNNNNNNN>`` — POSIX-atomic;
   the injection site ``"checkpoint.commit"`` sits right before this
   rename, so chaos tests can kill mid-save and prove nothing corrupts;
3. ``latest_step()`` only ever sees fully renamed directories; stale
   ``.tmp-*`` dirs from killed saves are swept on construction.

There is deliberately NO separate "latest" marker file: the set of
committed directories IS the source of truth, so no ordering bug between
"write data" and "write marker" can exist.
"""
from __future__ import annotations

import json
import os
import shutil
from typing import Any, Dict, List, Optional, Tuple

from ..base import MXNetError
from ..observability.trace import active as _trace_active
from .faults import inject

__all__ = ["AtomicCheckpointer"]

_STEP_PREFIX = "step-"
_TMP_PREFIX = ".tmp-"
_STATE_FILE = "state.mxtpu"
_META_FILE = "meta.json"


class AtomicCheckpointer:
    """Commit-or-nothing step checkpoints under one directory.

    ``save(step, tree)`` takes a flat ``{name: NDArray}`` dict (see
    ``ShardedTrainer.state_dict()``); ``restore(step=None)`` returns
    ``(tree, meta)`` for the requested or latest committed step.
    ``max_to_keep`` garbage-collects oldest committed steps AFTER each
    successful commit (never before — a failed save must not eat the
    fallback).
    """

    def __init__(self, directory: str, max_to_keep: Optional[int] = None):
        self.directory = os.path.abspath(str(directory))
        self.max_to_keep = max_to_keep
        os.makedirs(self.directory, exist_ok=True)
        self._sweep_tmp()

    # ----------------------------------------------------------- inventory
    def _sweep_tmp(self):
        for name in os.listdir(self.directory):
            if not name.startswith(_TMP_PREFIX):
                continue
            path = os.path.join(self.directory, name)
            if name.startswith(_TMP_PREFIX + "old-"):
                # a re-commit moved a COMMITTED step aside and was killed
                # before finishing: if the step dir is gone, the aside
                # copy is the only committed state — recover it
                try:
                    step = int(name[len(_TMP_PREFIX + "old-"):].split("-")[0])
                except ValueError:
                    step = None
                if step is not None and not os.path.isdir(
                        self._step_dir(step)):
                    os.rename(path, self._step_dir(step))
                    continue
            shutil.rmtree(path, ignore_errors=True)

    def all_steps(self) -> List[int]:
        out = []
        for name in os.listdir(self.directory):
            if name.startswith(_STEP_PREFIX):
                try:
                    out.append(int(name[len(_STEP_PREFIX):]))
                except ValueError:
                    continue
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"{_STEP_PREFIX}{step:08d}")

    # ---------------------------------------------------------------- save
    def save(self, step: int, tree: Dict[str, Any],
             meta: Optional[dict] = None) -> str:
        """Write and atomically commit one step.  Returns the committed
        path.  Re-committing an existing step replaces it (the
        resume-replays-a-step case; earlier steps stay as fallback)."""
        inject("checkpoint.save")
        tr = _trace_active()
        if tr is None:
            return self._save(step, tree, meta)
        # context-managed like every other site, so a failed save tags
        # its span with error=<type> instead of looking clean
        with tr.span("checkpoint.save", step=int(step)):
            return self._save(step, tree, meta)

    def _save(self, step: int, tree: Dict[str, Any],
              meta: Optional[dict]) -> str:
        from ..utils.serialization import save as _save

        step = int(step)
        tmp = os.path.join(self.directory,
                           f"{_TMP_PREFIX}{step:08d}-{os.getpid()}")
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        _save(os.path.join(tmp, _STATE_FILE), dict(tree))
        with open(os.path.join(tmp, _META_FILE), "w") as f:
            json.dump({"step": step, **(meta or {})}, f)
        inject("checkpoint.commit")
        final = self._step_dir(step)
        aside = None
        if os.path.exists(final):
            # re-committing an existing step: move the old dir ASIDE
            # (rename, not delete) so a kill between here and the commit
            # rename still leaves one committed copy of this step —
            # .old- dirs are swept with the tmp dirs on construction
            aside = os.path.join(self.directory,
                                 f"{_TMP_PREFIX}old-{step:08d}-{os.getpid()}")
            shutil.rmtree(aside, ignore_errors=True)
            os.rename(final, aside)
        try:
            os.rename(tmp, final)      # THE commit point
        except BaseException:
            if aside is not None and not os.path.exists(final):
                os.rename(aside, final)    # roll the old commit back in
                aside = None
            raise
        if aside is not None:
            shutil.rmtree(aside, ignore_errors=True)
        self._gc()
        # fleet counter for DIRECT checkpointer users; ResilientLoop
        # additionally counts its own commits into stats()["resilience"]
        try:
            from ..observability.registry import default_registry
            default_registry().counter(
                "mxtpu_checkpoint_commits_total",
                help="atomic checkpoint commits (rename succeeded)").inc()
        except Exception:
            pass
        return final

    def _gc(self):
        if self.max_to_keep is None:
            return
        steps = self.all_steps()
        for s in steps[:max(0, len(steps) - self.max_to_keep)]:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)

    # ------------------------------------------------------------- restore
    def restore(self, step: Optional[int] = None) \
            -> Tuple[Dict[str, Any], dict]:
        from ..utils.serialization import load as _load

        inject("checkpoint.restore")
        if step is None:
            step = self.latest_step()
        if step is None:
            raise MXNetError(
                f"no checkpoint found under {self.directory} "
                f"(all_steps={self.all_steps()})")
        path = self._step_dir(int(step))
        if not os.path.isdir(path):
            raise MXNetError(
                f"no checkpoint for step {step} under {self.directory} "
                f"(all_steps={self.all_steps()})")
        tree = _load(os.path.join(path, _STATE_FILE))
        with open(os.path.join(path, _META_FILE)) as f:
            meta = json.load(f)
        return tree, meta

    def __repr__(self):
        return (f"AtomicCheckpointer({self.directory!r}, "
                f"steps={self.all_steps()})")
