"""``mx.contrib`` — control-flow operators and contrib surface.

Parity target: src/operator/control_flow.cc (`foreach`, `while_loop`,
`cond` higher-order ops; SURVEY.md §2.3) exposed as
``mx.nd.contrib.foreach`` etc.

TPU-first dispatch per mode:
- hybridized/traced (inputs are JAX tracers): lower to ``lax.scan`` /
  ``lax.while_loop`` / ``lax.cond`` so the loop is ONE XLA op (no unrolling,
  no retraces) — this is what the subgraph executor of control_flow.cc
  becomes under a real compiler.
- eager while autograd records: a Python loop, so every step's ops land on
  the tape and gradients flow to closure parameters exactly as MXNet's
  imperative control flow does.
- plain eager: Python loop (simple, correct).
"""
from __future__ import annotations

from typing import Callable, List, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from .. import base as _base
from ..ndarray import NDArray
from .. import ndarray as _ops

__all__ = ["foreach", "while_loop", "cond"]


def _is_traced(*nds) -> bool:
    for x in nds:
        if isinstance(x, NDArray) and isinstance(x.jax, jax.core.Tracer):
            return True
    return False


def _as_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def foreach(body: Callable, data, init_states):
    """Iterate `body(item, states) -> (outputs, new_states)` over axis 0 of
    `data` (parity: mx.nd.contrib.foreach)."""
    data_list = _as_list(data)
    states = _as_list(init_states)
    multi_data = isinstance(data, (list, tuple))
    multi_states = isinstance(init_states, (list, tuple))

    if _is_traced(*data_list, *states):
        def scan_body(carry, xs):
            st = [NDArray(c) for c in carry]
            item = [NDArray(x) for x in xs]
            out, new_st = body(item if multi_data else item[0],
                               st if multi_states else st[0])
            out_l = _as_list(out)
            new_l = _as_list(new_st)
            return (tuple(s.jax for s in new_l),
                    tuple(o.jax for o in out_l))

        carry0 = tuple(s.jax for s in states)
        xs = tuple(d.jax for d in data_list)
        final, stacked = lax.scan(scan_body, carry0, xs)
        outs = [NDArray(o) for o in stacked]
        fst = [NDArray(s) for s in final]
        return (outs if (multi_data or len(outs) > 1) and len(outs) != 1
                else outs[0],
                fst if multi_states else fst[0])

    # eager: python loop (tape-visible)
    n = data_list[0].shape[0]
    step_outs: List[List[NDArray]] = []
    cur = states
    for i in range(n):
        item = [d[i] for d in data_list]
        out, new_st = body(item if multi_data else item[0],
                           cur if multi_states else cur[0])
        step_outs.append(_as_list(out))
        cur = _as_list(new_st)
    stacked = [_ops.stack(*[s[j] for s in step_outs], axis=0)
               for j in range(len(step_outs[0]))]
    return (stacked if len(stacked) != 1 else stacked[0],
            cur if multi_states else cur[0])


def while_loop(cond_fn: Callable, func: Callable, loop_vars,
               max_iterations: int = None):
    """`while cond(vars): vars = func(vars)` with per-step outputs stacked
    and padded to max_iterations (parity: mx.nd.contrib.while_loop)."""
    vars_list = _as_list(loop_vars)
    multi = isinstance(loop_vars, (list, tuple))
    if max_iterations is None:
        raise _base.MXNetError("while_loop requires max_iterations")

    if _is_traced(*vars_list):
        # fixed-trip scan with an active mask: XLA-friendly (static shape),
        # semantically identical incl. output padding with zeros
        def scan_body(carry, _):
            active, vals = carry
            nd_vals = [NDArray(v) for v in vals]
            packed = nd_vals if multi else nd_vals[0]
            pred = cond_fn(*nd_vals) if multi else cond_fn(nd_vals[0])
            pred_v = pred.jax if isinstance(pred, NDArray) else pred
            pred_v = jnp.reshape(pred_v, ()).astype(jnp.bool_)
            take = jnp.logical_and(active, pred_v)
            step_out, new_vals = func(*nd_vals) if multi else func(nd_vals[0])
            out_l = [o.jax for o in _as_list(step_out)]
            new_l = [v.jax for v in _as_list(new_vals)]
            sel_vals = tuple(
                jnp.where(take, nv, ov) for nv, ov in zip(new_l, vals))
            sel_outs = tuple(
                jnp.where(take, o, jnp.zeros_like(o)) for o in out_l)
            return (take, sel_vals), sel_outs

        carry0 = (jnp.asarray(True), tuple(v.jax for v in vars_list))
        (_, final), outs = lax.scan(scan_body, carry0, None,
                                    length=max_iterations)
        out_nds = [NDArray(o) for o in outs]
        fin_nds = [NDArray(v) for v in final]
        return (out_nds if len(out_nds) != 1 else out_nds[0],
                fin_nds if multi else fin_nds[0])

    # eager
    cur = vars_list
    step_outs = []
    steps = 0
    while steps < max_iterations:
        pred = cond_fn(*cur) if multi else cond_fn(cur[0])
        if not bool(pred.asnumpy() if isinstance(pred, NDArray) else pred):
            break
        out, new_vals = func(*cur) if multi else func(cur[0])
        step_outs.append(_as_list(out))
        cur = _as_list(new_vals)
        steps += 1
    if step_outs:
        stacked = []
        for j in range(len(step_outs[0])):
            col = [s[j] for s in step_outs]
            st = _ops.stack(*col, axis=0)
            pad = max_iterations - len(col)
            if pad > 0:
                zeros = _ops.zeros((pad,) + tuple(col[0].shape))
                st = _ops.concat(st, zeros.astype(str(st.dtype)), dim=0)
            stacked.append(st)
    else:
        stacked = []
    return (stacked if len(stacked) != 1 else stacked[0],
            cur if multi else cur[0])


def cond(pred, then_func: Callable, else_func: Callable, inputs=None):
    """Conditional execution (parity: mx.nd.contrib.cond)."""
    pred_v = pred.jax if isinstance(pred, NDArray) else pred
    if _is_traced(pred if isinstance(pred, NDArray) else NDArray(pred_v)):
        def then_b(_):
            out = then_func()
            return tuple(o.jax for o in _as_list(out))

        def else_b(_):
            out = else_func()
            return tuple(o.jax for o in _as_list(out))

        p = jnp.reshape(pred_v, ()).astype(jnp.bool_)
        outs = lax.cond(p, then_b, else_b, operand=None)
        nds = [NDArray(o) for o in outs]
        return nds if len(nds) != 1 else nds[0]
    take = bool(pred.asnumpy() if isinstance(pred, NDArray) else pred_v)
    return then_func() if take else else_func()


def __getattr__(name):
    # upstream scripts reach contrib OPS as mx.nd.contrib.<op>
    # (arange_like, interleaved_matmul_selfatt_*, div_sqrt_dim, ...);
    # the kernels live in the main op namespace here.  Only REGISTERED
    # ops (ops.__all__) forward through THIS hook; note the module's own
    # runtime imports (jax/lax/NDArray/typing) remain visible as plain
    # module attributes, as in any Python module.
    from ..ndarray import ops as _ops
    if not name.startswith("_") and name in _ops.__all__:
        return getattr(_ops, name)
    raise AttributeError(name)
