"""INT8 quantization (parity: src/operator/quantization/*.{cc,cu} +
python/mxnet/contrib/quantization.py, SURVEY.md §2.3).

TPU-first design: int8 matmuls run on the MXU via
``lax.dot_general(..., preferred_element_type=int32)`` — the TPU analogue
of the oneDNN/cuDNN int8 paths — with per-tensor scales applied as cheap
f32 epilogues that XLA fuses.  The op surface keeps MXNet's contract
(quantize / quantize_v2 / dequantize / requantize returning (data, min,
max) triples), and ``quantize_net`` mirrors ``quantize_model``:
calibrate activation ranges over a dataset (naive min/max or entropy/KL
histogram), then swap Dense layers for int8-weight equivalents.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as onp

from .. import base as _base
from ..gluon.block import HybridBlock
from ..gluon.nn import Dense
from ..ndarray import NDArray
from ..ndarray.ops import _as_nd, invoke

__all__ = ["quantize", "quantize_v2", "dequantize", "requantize",
           "calib_entropy_threshold", "quantize_net", "QuantizedDense",
           "QuantizedConv2D", "quantized_pooling"]


# ------------------------------------------------------------------- ops

def _q_params(mn, mx, dtype):
    """Symmetric int8 / affine uint8 scale-zero from a float range."""
    if dtype == "int8":
        scale = jnp.maximum(jnp.maximum(jnp.abs(mn), jnp.abs(mx)),
                            1e-8) / 127.0
        zero = jnp.zeros_like(scale)
    elif dtype == "uint8":
        scale = jnp.maximum(mx - mn, 1e-8) / 255.0
        zero = jnp.round(-mn / scale)
    else:
        raise _base.MXNetError(f"unsupported quantized dtype {dtype}")
    return scale, zero


def _affine_quantize(d, mn, mx, out_type):
    """Shared quantization kernel: scale/round/clip/cast."""
    scale, zero = _q_params(mn, mx, out_type)
    lo, hi = (-127, 127) if out_type == "int8" else (0, 255)
    q = jnp.clip(jnp.round(d / scale) + zero, lo, hi)
    return q.astype(jnp.int8 if out_type == "int8" else jnp.uint8)


def quantize(data, min_range, max_range, out_type="int8"):
    """(qdata, min, max): affine-quantize with an explicit range
    (parity: _contrib_quantize)."""
    data, min_range, max_range = (_as_nd(x) for x in
                                  (data, min_range, max_range))

    def f(d, mn, mx):
        return _affine_quantize(d, mn, mx, out_type), mn, mx

    return invoke("quantize", f, [data, min_range, max_range],
                  differentiable=False)


def quantize_v2(data, min_calib_range=None, max_calib_range=None,
                out_type="int8"):
    """Auto-ranging quantize (parity: _contrib_quantize_v2)."""
    data = _as_nd(data)

    def f(d):
        if min_calib_range is not None and max_calib_range is not None:
            mn = jnp.asarray(min_calib_range, jnp.float32)
            mx = jnp.asarray(max_calib_range, jnp.float32)
        else:
            mn = jnp.min(d).astype(jnp.float32)
            mx = jnp.max(d).astype(jnp.float32)
        return _affine_quantize(d, mn, mx, out_type), mn, mx

    return invoke("quantize_v2", f, [data], differentiable=False)


def dequantize(qdata, min_range, max_range, out_type="float32"):
    """Inverse of :func:`quantize` (parity: _contrib_dequantize)."""
    qdata, min_range, max_range = (_as_nd(x) for x in
                                   (qdata, min_range, max_range))
    in_int8 = str(qdata.dtype) == "int8"

    def f(q, mn, mx):
        scale, zero = _q_params(mn, mx, "int8" if in_int8 else "uint8")
        return ((q.astype(jnp.float32) - zero) * scale).astype(out_type)

    return invoke("dequantize", f, [qdata, min_range, max_range],
                  differentiable=False)


def requantize(qdata, min_range, max_range, min_calib_range,
               max_calib_range):
    """int32 accum → int8 with a narrower calibrated range (parity:
    _contrib_requantize)."""
    qdata, min_range, max_range = (_as_nd(x) for x in
                                   (qdata, min_range, max_range))

    def f(q, mn, mx):
        in_scale = jnp.maximum(jnp.maximum(jnp.abs(mn), jnp.abs(mx)),
                               1e-8) / (2.0 ** 31 - 1)
        cm = jnp.asarray(min_calib_range, jnp.float32)
        cx = jnp.asarray(max_calib_range, jnp.float32)
        out_scale, _ = _q_params(cm, cx, "int8")
        val = q.astype(jnp.float32) * in_scale
        out = jnp.clip(jnp.round(val / out_scale), -127, 127)
        return out.astype(jnp.int8), cm, cx

    return invoke("requantize", f, [qdata, min_range, max_range],
                  differentiable=False)


# ------------------------------------------------------------ calibration

def calib_entropy_threshold(hist, hist_edges, num_quantized_bins=255):
    """KL-divergence-optimal |threshold| from an absolute-value histogram
    (parity: the entropy mode of quantization.py's _LayerHistogramCollector
    / get_optimal_threshold)."""
    hist = onp.asarray(hist, onp.float64)
    edges = onp.asarray(hist_edges)
    nbins = len(hist)
    csum = onp.concatenate([[0.0], onp.cumsum(hist)])
    total = csum[-1]
    best_kl, best_t = onp.inf, edges[-1]
    start = max(num_quantized_bins // 2, 1)
    for i in range(start, nbins + 1):
        if total == 0:
            continue
        # candidate distribution p: hist[:i] with the tail folded into the
        # last bin
        p_last = hist[i - 1] + (total - csum[i])
        p_sum = total
        # quantize hist[:i] into num_quantized_bins segments (vectorized
        # via cumsum over segment boundaries)
        idx = onp.linspace(0, i, num_quantized_bins + 1).astype(int)
        idx_hi = onp.maximum(idx[1:], idx[:-1] + 1)
        seg_sum = csum[onp.minimum(idx_hi, i)] - csum[idx[:-1]]
        nz_csum = onp.concatenate([[0], onp.cumsum(hist[:i] > 0)])
        seg_nz = nz_csum[onp.minimum(idx_hi, i)] - nz_csum[idx[:-1]]
        # expand back: bin j of segment s gets seg_sum[s]/seg_nz[s] where
        # hist[j] > 0
        seg_of = onp.searchsorted(idx[1:], onp.arange(i), side="right")
        seg_of = onp.minimum(seg_of, num_quantized_bins - 1)
        with onp.errstate(divide="ignore", invalid="ignore"):
            fill = onp.where(seg_nz > 0, seg_sum / onp.maximum(seg_nz, 1),
                             0.0)
        q = onp.where(hist[:i] > 0, fill[seg_of], 0.0)
        qs = q.sum()
        if qs == 0:
            continue
        pm = hist[:i] / p_sum
        pm_last = p_last / p_sum
        qm = q / qs
        mask = hist[:i] > 0
        pm_eff = pm.copy()
        pm_eff[-1] = pm_last
        mask[-1] = pm_last > 0
        kl = float((pm_eff[mask] * onp.log(
            pm_eff[mask] / onp.maximum(qm[mask], 1e-12))).sum())
        if kl < best_kl:
            best_kl, best_t = kl, edges[i]
    return float(best_t)


class _Collector:
    """Records per-layer |activation| statistics during calibration."""

    def __init__(self, mode, num_bins=8001):
        self.mode = mode
        self.num_bins = num_bins
        self.minmax: Dict[str, List[float]] = {}
        self.hists: Dict[str, onp.ndarray] = {}
        self.hist_max: Dict[str, float] = {}

    def collect(self, name, arr):
        a = onp.asarray(arr)
        mn, mx = float(a.min()), float(a.max())
        if name in self.minmax:
            self.minmax[name][0] = min(self.minmax[name][0], mn)
            self.minmax[name][1] = max(self.minmax[name][1], mx)
        else:
            self.minmax[name] = [mn, mx]
        if self.mode == "entropy":
            amax = max(abs(mn), abs(mx), 1e-8)
            if name not in self.hists or amax > self.hist_max[name]:
                # re-bin on range growth (coarse but faithful)
                old_h = self.hists.get(name)
                old_m = self.hist_max.get(name, amax)
                self.hist_max[name] = amax = max(amax, old_m)
                self.hists[name] = onp.zeros(self.num_bins)
                if old_h is not None:
                    centers = (onp.arange(self.num_bins) + 0.5) * \
                        old_m / self.num_bins
                    reb, _ = onp.histogram(centers, bins=self.num_bins,
                                           range=(0, amax), weights=old_h)
                    self.hists[name] += reb
            h, _ = onp.histogram(onp.abs(a), bins=self.num_bins,
                                 range=(0, self.hist_max[name]))
            self.hists[name] += h

    def ranges(self):
        out = {}
        for name, (mn, mx) in self.minmax.items():
            if self.mode == "entropy" and name in self.hists:
                edges = onp.linspace(0, self.hist_max[name],
                                     self.num_bins + 1)
                t = calib_entropy_threshold(self.hists[name], edges)
                out[name] = (-t if mn < 0 else 0.0, t)
            else:
                out[name] = (mn, mx)
        return out


# ------------------------------------------------------------ layers/net

class QuantizedDense(HybridBlock):
    """int8-weight Dense: activations quantize dynamically (or with a
    calibrated range), the matmul accumulates in int32 on the MXU, and
    the f32 epilogue applies scales + bias (parity:
    _contrib_quantized_fully_connected)."""

    def __init__(self, dense: Dense, min_calib=None, max_calib=None,
                 **kwargs):
        super().__init__(**kwargs)
        from ..ndarray import array as nd_array
        wnp = dense.weight.data().asnumpy()
        w_scale = float(max(abs(wnp.min()), abs(wnp.max()), 1e-8)) / 127.0
        wq = onp.clip(onp.round(wnp / w_scale), -127, 127).astype(onp.int8)
        # int8 weights, scale, bias AND the calibrated activation range
        # are real Parameters so the quantized net checkpoints fully
        # through save_parameters/load_parameters (set_data on a fresh
        # Parameter establishes shape+value directly)
        self.qweight = self.params.get("qweight", shape=wq.shape,
                                       dtype="int8", grad_req="null")
        self.qweight.set_data(nd_array(wq, dtype="int8"))
        self.wscale = self.params.get("wscale", shape=(1,),
                                      dtype="float32", grad_req="null")
        self.wscale.set_data(nd_array([w_scale]))
        # nan means "no calibration: quantize activations dynamically"
        self.acts_range = self.params.get("acts_range", shape=(2,),
                                          dtype="float32", grad_req="null")
        self.acts_range.set_data(nd_array(
            [float("nan") if min_calib is None else min_calib,
             float("nan") if max_calib is None else max_calib]))
        if dense.bias is not None:
            bnp = dense.bias.data().asnumpy()
            self.bias = self.params.get("bias", shape=bnp.shape,
                                        dtype="float32", grad_req="null")
            self.bias.set_data(nd_array(bnp))
        else:
            self.bias = None
        self._units = dense._units
        self._flatten = dense._flatten
        self._activation = dense._activation

    def forward(self, x):
        x = _as_nd(x)
        wq = self.qweight.data().jax
        w_scale = self.wscale.data().jax[0]
        bias = None if self.bias is None else self.bias.data().jax
        crange = self.acts_range.data().jax

        def f(xv):
            shape = xv.shape
            if self._flatten and xv.ndim > 2:
                xv = xv.reshape(shape[0], -1)
            dyn = jnp.maximum(jnp.max(jnp.abs(xv)), 1e-8)
            calib = jnp.maximum(jnp.abs(crange[0]), jnp.abs(crange[1]))
            amax = jnp.where(jnp.isnan(crange[0]), dyn, calib)
            x_scale = amax / 127.0
            xq = jnp.clip(jnp.round(xv / x_scale), -127, 127).astype(
                jnp.int8)
            acc = jax.lax.dot_general(
                xq, wq, (((xv.ndim - 1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32)
            out = acc.astype(jnp.float32) * (x_scale * w_scale)
            if bias is not None:
                out = out + bias
            if self._activation is not None:
                from ..ndarray.ops import ACTIVATION_FNS
                out = ACTIVATION_FNS[self._activation](out)
            return out

        return invoke("quantized_dense", f, [x], differentiable=False)

    def __repr__(self):
        return (f"QuantizedDense({self.qweight.shape[1]} -> "
                f"{self._units}, int8)")


class QuantizedConv2D(HybridBlock):
    """int8-weight NCHW convolution: per-output-channel weight scales,
    int32 MXU accumulation via ``lax.conv_general_dilated(...,
    preferred_element_type=int32)``, f32 scale+bias epilogue (parity:
    _contrib_quantized_conv / src/operator/quantization/quantized_conv.cc —
    upstream runs these through cuDNN/oneDNN int8; the MXU int8 path is
    the TPU-native analogue)."""

    def __init__(self, conv, min_calib=None, max_calib=None, **kwargs):
        super().__init__(**kwargs)
        from ..ndarray import array as nd_array
        wnp = conv.weight.data().asnumpy()          # (O, I/g, kH, kW)
        # per-output-channel symmetric scales: tighter than the per-tensor
        # range upstream uses, still a broadcast f32 epilogue on TPU
        absmax = onp.maximum(onp.abs(wnp).reshape(wnp.shape[0], -1)
                             .max(axis=1), 1e-8)
        w_scale = (absmax / 127.0).astype(onp.float32)
        wq = onp.clip(onp.round(wnp / w_scale[:, None, None, None]),
                      -127, 127).astype(onp.int8)
        self.qweight = self.params.get("qweight", shape=wq.shape,
                                       dtype="int8", grad_req="null")
        self.qweight.set_data(nd_array(wq, dtype="int8"))
        self.wscale = self.params.get("wscale", shape=w_scale.shape,
                                      dtype="float32", grad_req="null")
        self.wscale.set_data(nd_array(w_scale))
        self.acts_range = self.params.get("acts_range", shape=(2,),
                                          dtype="float32", grad_req="null")
        self.acts_range.set_data(nd_array(
            [float("nan") if min_calib is None else min_calib,
             float("nan") if max_calib is None else max_calib]))
        if conv.bias is not None:
            bnp = conv.bias.data().asnumpy()
            self.bias = self.params.get("bias", shape=bnp.shape,
                                        dtype="float32", grad_req="null")
            self.bias.set_data(nd_array(bnp))
        else:
            self.bias = None
        self._strides = conv._strides
        self._padding = conv._padding
        self._dilation = conv._dilation
        self._groups = conv._groups
        self._channels = conv._channels
        self._activation = conv._activation

    def forward(self, x):
        x = _as_nd(x)
        wq = self.qweight.data().jax
        w_scale = self.wscale.data().jax
        bias = None if self.bias is None else self.bias.data().jax
        crange = self.acts_range.data().jax
        stride, pad, dil, groups = (self._strides, self._padding,
                                    self._dilation, self._groups)

        def f(xv):
            dyn = jnp.maximum(jnp.max(jnp.abs(xv)), 1e-8)
            calib = jnp.maximum(jnp.abs(crange[0]), jnp.abs(crange[1]))
            amax = jnp.where(jnp.isnan(crange[0]), dyn, calib)
            x_scale = amax / 127.0
            xq = jnp.clip(jnp.round(xv / x_scale), -127, 127).astype(
                jnp.int8)
            acc = jax.lax.conv_general_dilated(
                xq, wq, window_strides=stride,
                padding=tuple((p, p) for p in pad), rhs_dilation=dil,
                dimension_numbers=("NCHW", "OIHW", "NCHW"),
                feature_group_count=groups,
                preferred_element_type=jnp.int32)
            out = acc.astype(jnp.float32) * \
                (x_scale * w_scale)[None, :, None, None]
            if bias is not None:
                out = out + bias[None, :, None, None]
            if self._activation is not None:
                from ..ndarray.ops import ACTIVATION_FNS
                out = ACTIVATION_FNS[self._activation](out)
            return out

        return invoke("quantized_conv", f, [x], differentiable=False)

    def __repr__(self):
        return f"QuantizedConv2D({self._channels} ch, int8)"


def quantized_pooling(qdata, min_range, max_range, kernel=None, stride=None,
                      pad=None, pool_type="max", global_pool=False):
    """Pooling on int8 data keeping the (q, min, max) triple (parity:
    _contrib_quantized_pooling / quantized_pooling.cc).  Max pooling is
    order-preserving so it runs directly on int8; avg pooling accumulates
    in int32 and rounds back to the same scale."""
    from ..ndarray.ops import Pooling
    qdata, min_range, max_range = (_as_nd(x) for x in
                                   (qdata, min_range, max_range))
    if pool_type == "max":
        out = Pooling(qdata.astype("int32"), kernel=kernel, stride=stride,
                      pad=pad, pool_type="max", global_pool=global_pool)
        out = out.astype("int8")
    elif pool_type == "avg":
        acc = Pooling(qdata.astype("float32"), kernel=kernel, stride=stride,
                      pad=pad, pool_type="avg", global_pool=global_pool)
        out = invoke("quantized_avg_round",
                     lambda a: jnp.clip(jnp.round(a), -127, 127)
                     .astype(jnp.int8), [acc], differentiable=False)
    else:
        raise _base.MXNetError(f"unsupported pool_type {pool_type}")
    return out, min_range, max_range


def quantize_net(net, calib_data=None, calib_mode="naive",
                 quantized_dtype="int8", exclude_layers=None,
                 num_calib_batches=None):
    """Swap Dense layers of a Gluon net for int8 equivalents (parity:
    contrib.quantization.quantize_net).

    calib_mode: 'none' (dynamic activation ranges), 'naive' (min/max over
    calib_data), 'entropy' (KL-optimal thresholds).  calib_data yields
    input batches (NDArray or DataBatch).
    """
    if quantized_dtype != "int8":
        raise _base.MXNetError("TPU build quantizes to int8 (MXU-native)")
    exclude = set(exclude_layers or ())

    calib_iter = iter(calib_data) if calib_data is not None else None
    first_batch = next(calib_iter, None) if calib_iter is not None else None

    from ..gluon.nn import Conv2D

    def walk(block, prefix=""):
        for name, child in list(block._children.items()):
            path = f"{prefix}{name}"
            if isinstance(child, (Dense, Conv2D)) and path not in exclude:
                if child.weight._data is not None:
                    targets.append((block, name, path, child))
                else:
                    deferred.append(path)
            else:
                walk(child, path + ".")

    targets, deferred = [], []   # (parent, attr_name, child_name, dense)
    walk(net)
    if deferred and first_batch is not None:
        # settle deferred-init Dense shapes with one forward, then re-walk
        data = first_batch.data[0] if hasattr(first_batch, "data") \
            else first_batch
        net(data)
        targets, deferred = [], []
        walk(net)
    if deferred:
        raise _base.MXNetError(
            f"Dense/Conv2D layers {deferred} have uninitialized (deferred) "
            "weights — run a forward pass or pass calib_data so "
            "quantize_net can see their shapes")

    ranges: Dict[str, tuple] = {}
    if calib_data is not None and calib_mode in ("naive", "entropy"):
        collector = _Collector(calib_mode)
        hooked = []
        for _, _, path, dense in targets:
            def mk(path):
                def hook(block, inputs):
                    collector.collect(path, inputs[0].asnumpy())
                return hook
            hooked.append((dense, dense.register_forward_pre_hook(mk(path))))
        try:
            n = 0
            import itertools
            for batch in itertools.chain(
                    [first_batch] if first_batch is not None else [],
                    calib_iter):
                data = batch.data[0] if hasattr(batch, "data") else batch
                net(data)
                n += 1
                if num_calib_batches is not None and \
                        n >= num_calib_batches:
                    break
        finally:
            for dense, h in hooked:
                dense._forward_pre_hooks.remove(h)
        ranges = collector.ranges()

    for parent, attr, path, layer in targets:
        r = ranges.get(path)
        cls = QuantizedDense if isinstance(layer, Dense) else QuantizedConv2D
        q = cls(layer, min_calib=r[0] if r else None,
                max_calib=r[1] if r else None)
        parent.register_child(q, attr)
        if getattr(parent, attr, None) is layer:
            setattr(parent, attr, q)
    return net
