"""Device mesh management (TPU-native replacement for MXNet's context lists
and KVStore comm topology; SURVEY.md §2.4/§7.1).

MXNet scales by enumerating contexts (``[mx.gpu(0..7)]``) and reducing
gradients through KVStore comm trees.  The TPU-native realization is a named
:class:`jax.sharding.Mesh`: every parallelism strategy is an axis name, and
XLA inserts the collectives (psum over ICI) that CommDevice/NCCL performed
by hand (parity: src/kvstore/comm.h — the topology role, not the code).

Canonical axes (all always present; unused axes have size 1 so sharding
rules can reference them unconditionally):

- ``pp``  pipeline stages (outermost: lowest-bandwidth links)
- ``dp``  data parallel replicas
- ``ep``  expert parallel (MoE)
- ``sp``  sequence/context parallel (ring attention)
- ``tp``  tensor parallel (innermost: highest-bandwidth ICI neighbors)
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import jax
import numpy as onp
from jax.sharding import Mesh

from .. import base as _base

AXES = ("pp", "dp", "ep", "sp", "tp")

_current: List[Mesh] = []


def make_mesh(dp: Optional[int] = None, tp: int = 1, pp: int = 1, sp: int = 1,
              ep: int = 1, devices: Optional[Sequence] = None) -> Mesh:
    """Build a 5-axis mesh over ``devices`` (default: all local devices).

    ``dp=None`` absorbs whatever device count the other axes leave over.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    fixed = tp * pp * sp * ep
    if dp is None:
        if n % fixed:
            raise _base.MXNetError(
                f"{n} devices not divisible by tp*pp*sp*ep={fixed}")
        dp = n // fixed
    if dp * fixed != n:
        raise _base.MXNetError(
            f"mesh {dp}x{fixed} needs {dp * fixed} devices, have {n}")
    sizes = {"pp": pp, "dp": dp, "ep": ep, "sp": sp, "tp": tp}
    grid = onp.asarray(devices, dtype=object).reshape(
        [sizes[a] for a in AXES])
    return Mesh(grid, AXES)


def current_mesh() -> Optional[Mesh]:
    """Innermost active mesh (set via ``with use_mesh(m):`` or default)."""
    if _current:
        return _current[-1]
    try:
        from jax._src import mesh as _mesh_lib
        m = _mesh_lib.thread_resources.env.physical_mesh
    except (ImportError, AttributeError):
        m = jax.interpreters.pxla.thread_resources.env.physical_mesh
    if len(m.axis_names) > 0:
        return m
    return None


class use_mesh:
    """Context manager installing a mesh as the ambient default."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh

    def __enter__(self):
        _current.append(self.mesh)
        return self.mesh

    def __exit__(self, *a):
        _current.pop()


def axis_size(mesh: Mesh, axis: str) -> int:
    return dict(zip(mesh.axis_names, mesh.devices.shape)).get(axis, 1)
