"""ShardedTrainer: the whole training step as ONE jitted, sharded XLA
computation over the device mesh.

Parity note: this subsumes three MXNet mechanisms at once (SURVEY.md §3.2/3.3)
— CachedOp forward/backward (src/imperative/cached_op.cc), KVStore gradient
allreduce (src/kvstore/comm.h: Comm::Reduce → here a psum XLA inserts from
the dp-sharded batch), and the fused optimizer update ops
(src/operator/optimizer_op.cc: here the *same* mxnet_tpu.optimizer.Optimizer
instance runs inside the trace, so every MXNet optimizer works sharded,
unmodified).  Gluon's ``Trainer`` keeps the imperative API for single-device
flows; ShardedTrainer is the pjit path that scales it to a pod.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import base as _base
from .. import optimizer as opt_mod
from .. import random as _random
from ..ndarray import NDArray
from ..observability.trace import active as _trace_active
from ..resilience.faults import inject as _inject, poison as _poison
from ..ndarray.ndarray import swap_values
from .mesh import current_mesh, use_mesh
from .sharding import (ShardingRules, batch_spec, logical_axes_of,
                       mesh_device_put as _mesh_device_put, shard_params)


def _flatten_state(state) -> Tuple[List[NDArray], Any]:
    """Flatten an optimizer state pytree (None / NDArray / nested tuples)."""
    leaves: List[NDArray] = []

    def walk(s):
        if s is None:
            return ("none",)
        if isinstance(s, NDArray):
            leaves.append(s)
            return ("leaf",)
        if isinstance(s, (tuple, list)):
            return ("seq", type(s) is list, [walk(x) for x in s])
        raise _base.MXNetError(f"unsupported optimizer state {type(s)}")

    tree = walk(state)
    return leaves, tree


def _wrap_state(tree, it) -> Any:
    """Rebuild a state pytree with fresh NDArrays around traced leaves."""
    kind = tree[0]
    if kind == "none":
        return None
    if kind == "leaf":
        return NDArray(next(it))
    _, is_list, subs = tree
    seq = [_wrap_state(s, it) for s in subs]
    return seq if is_list else tuple(seq)


def _state_leaves(state_nd) -> List[NDArray]:
    leaves, _ = _flatten_state(state_nd)
    return leaves


class ShardedTrainer:
    """Train a Gluon block SPMD over a mesh (parity role: gluon.Trainer +
    KVStore ``dist_sync_device``, re-expressed as pjit).

    Parameters
    ----------
    net : Block — initialized (or initializable via one forward) model.
    optimizer : str or Optimizer — any registered MXNet optimizer.
    loss : callable(out, *labels) -> NDArray, reduced to scalar mean.
    mesh : jax.sharding.Mesh (default: ambient/current mesh).
    rules : ShardingRules mapping logical param axes → mesh axes.
    data_specs/label_specs : optional explicit PartitionSpecs per input;
        default shards dim0 over ``dp`` (and ``seq_axis`` over ``sp``).
    donate : donate param/state buffers to the step (XLA in-place update,
        the static_alloc analogue).
    grad_accum : microbatch count — the batch splits into ``grad_accum``
        microbatches run through ``lax.scan`` INSIDE the one jitted step,
        gradients accumulated in f32 and averaged before the single
        optimizer update.  Activation memory is O(batch/grad_accum)
        while the optimizer sees the full effective batch (the
        grad_req='add' accumulation idiom, compiled).  Batch dim must be
        divisible by grad_accum (and the microbatch by dp).
    guard_nonfinite : compile the training-health guardrails into the
        step (docs/guardrails.md): an ``all_finite`` flag over loss +
        gradients is computed IN-GRAPH and the optimizer update is
        applied through ``jnp.where`` selects, so a non-finite step
        leaves params/aux/optimizer state bit-identical — no
        ``lax.cond`` divergence, no recompile, no extra host sync.
        ``step()`` then returns ``(loss, all_finite)`` (both lazy
        NDArrays) instead of the bare loss.
    clip_global_norm : optional in-graph global-norm gradient clipping
        (the unscaled gradient's global L2 norm is capped at this value
        before the update).  Implies the guarded step.
    loss_scaler : an :class:`mxnet_tpu.amp.LossScaler` whose dynamic
        schedule (init_scale / scale_factor / scale_window) is compiled
        into the step: the loss is scaled in-graph, gradients unscaled
        before clipping/update, and the scale shrinks on a non-finite
        step / grows after ``scale_window`` consecutive finite ones —
        all as traced scalars, so the scale changing never recompiles.
        ``amp.init_trainer(trainer)`` attaches one for you.  Implies the
        guarded step.
    """

    def __init__(self, net, optimizer, loss=None, optimizer_params=None,
                 mesh: Optional[Mesh] = None,
                 rules: Optional[ShardingRules] = None,
                 data_specs=None, label_specs=None, seq_axis: Optional[int] = None,
                 donate: bool = True, donate_batch: bool = False,
                 grad_accum: int = 1,
                 guard_nonfinite: bool = False,
                 clip_global_norm: Optional[float] = None,
                 loss_scaler=None):
        self.net = net
        self.loss = loss
        if grad_accum != int(grad_accum) or int(grad_accum) < 1:
            raise _base.MXNetError(
                f"grad_accum must be a positive integer, got {grad_accum}")
        self._grad_accum = int(grad_accum)
        self.mesh = mesh or current_mesh()
        if self.mesh is None:
            raise _base.MXNetError(
                "ShardedTrainer needs a mesh — parallel.make_mesh() first")
        self.rules = rules or ShardingRules()
        if isinstance(optimizer, opt_mod.Optimizer):
            self.optimizer = optimizer
        else:
            self.optimizer = opt_mod.create(optimizer,
                                            **(optimizer_params or {}))
        self._data_specs = data_specs
        self._label_specs = label_specs
        self._seq_axis = seq_axis
        self._donate = donate
        # batch-buffer donation: safe ONLY when every step's batch is a
        # single-use array (the DevicePrefetcher contract) — callers
        # that re-feed the same NDArray each step must leave this off,
        # so it is opt-in unlike param/state donation
        self._donate_batch = bool(donate_batch)
        self._guard_nonfinite = bool(guard_nonfinite)
        self._data_source = None   # attach_data_source: stats()/span stamp
        if clip_global_norm is not None and clip_global_norm <= 0:
            raise _base.MXNetError(
                f"clip_global_norm must be > 0, got {clip_global_norm}")
        self._clip_global_norm = clip_global_norm
        self._loss_scaler = loss_scaler
        self._amp_loss_scaler = loss_scaler   # amp duck-type parity
        self._scale_arr = None     # traced loss-scale state (device)
        self._good_arr = None      # consecutive-finite-step counter
        self._built = False
        self._step_fn = None
        self._trainable: List[Tuple[str, Any]] = []
        self._aux: List[Tuple[str, Any]] = []
        self._states: List[Any] = []       # NDArray pytrees, per trainable
        self._state_flat: List[NDArray] = []
        self._state_shardings: List[NamedSharding] = []
        self._pending_states: Optional[dict] = None
        self._ckpt_managers: Dict[str, Any] = {}
        # fleet counters (docs/observability.md): process-wide step and
        # guarded-bad-step counts, shared across trainer instances
        from ..observability.registry import default_registry
        self._obs_steps = default_registry().counter(
            "mxtpu_trainer_steps_total",
            help="ShardedTrainer.step calls, all trainers")

    # ----------------------------------------------------------- guardrails
    @property
    def _guarded(self) -> bool:
        return (self._guard_nonfinite or self._loss_scaler is not None
                or self._clip_global_norm is not None)

    def attach_loss_scaler(self, scaler=None):
        """Enable in-graph dynamic loss scaling (the guarded step) with
        the given :class:`~mxnet_tpu.amp.LossScaler`'s schedule — what
        ``amp.init_trainer`` calls.  Must run before the first
        ``build()``/``step()``: the schedule compiles into the step."""
        if self._built:
            raise _base.MXNetError(
                "attach_loss_scaler after the trainer is built: the "
                "scale schedule compiles into the jitted step — attach "
                "before the first build()/step()")
        if scaler is None:
            from .. import amp as _amp
            scaler = _amp.LossScaler()
        self._loss_scaler = scaler
        self._amp_loss_scaler = scaler
        return scaler

    @property
    def loss_scale(self) -> float:
        """Current dynamic loss scale (syncs the device scalar; 1.0
        when no scaler is attached)."""
        if self._scale_arr is not None:
            return float(self._scale_arr)
        if self._loss_scaler is not None:
            return float(self._loss_scaler.loss_scale)
        return 1.0

    # ------------------------------------------------------------------
    def _build(self, data, labels):
        net = self.net
        # settle deferred shapes with one forward — in inference mode so
        # BatchNorm running stats / dropout are untouched by shape
        # settling.  Preferred path is ABSTRACT (jax.eval_shape): shape
        # propagation without a single FLOP or per-op XLA compile — on a
        # real chip an eager full-batch settle of a large model costs
        # minutes of tiny-op compiles.  Fallbacks: eager on a batch-1
        # slice (dim0 = batch by the data_specs contract; param shapes
        # never depend on it), then eager on the real batch (models with
        # batch-shape contracts, e.g. GPipe microbatching).
        def _settle_slice(x):
            if isinstance(x, NDArray) and x.ndim >= 1 and x.shape[0] > 1:
                return x[0:1]
            return x

        def _abstract_settle():
            import jax

            def run(*jv):
                net(*[NDArray(v) for v in jv])
                return jnp.zeros(())

            jax.eval_shape(run, *[d.jax for d in data])

        with _base.training_mode(False):
            rec = _base.set_recording(False)
            # settling runs MoE layers inside eval_shape traces: open a
            # collection scope so the router doesn't warn about a foreign
            # trace, and drain whatever gets recorded (shape settling
            # computes no loss)
            aux_prev = _base.set_aux_collection(True)
            try:
                import jax
                before = {id(p): p._data.jax
                          for p in net.collect_params().values()
                          if p._data is not None}
                try:
                    _abstract_settle()
                    leaked = any(
                        p._data is not None
                        and isinstance(p._data.jax, jax.core.Tracer)
                        for p in net.collect_params().values())
                except Exception:
                    leaked = True
                if leaked:
                    # abstract settle failed or silently bound tracers (a
                    # forward that rebinds state inside the trace).  Restore
                    # pre-existing params, re-init any freshly allocated
                    # ones concretely, and settle eagerly.
                    for p in net.collect_params().values():
                        d = p._data
                        if d is None or not isinstance(d.jax,
                                                       jax.core.Tracer):
                            continue
                        if id(p) in before:
                            d._rebind(before[id(p)])
                        else:
                            p._data = None
                            p.initialize(force_reinit=True)
                    try:
                        net(*[_settle_slice(d) for d in data])
                    except Exception:
                        net(*data)
            finally:
                _base.set_recording(rec)
                _base.set_aux_collection(aux_prev)
                _base.pop_aux_losses()
        seen = set()
        for name, p in net.collect_params().items():
            if id(p) in seen:
                continue
            seen.add(id(p))
            if p._data is None:
                continue
            if p.grad_req != "null":
                self._trainable.append((name, p))
            else:
                self._aux.append((name, p))
        # optimizer states (NDArray pytrees, kept for save/load parity)
        self.optimizer.param_dict = {
            i: p for i, (_, p) in enumerate(self._trainable)}
        for i, (_, p) in enumerate(self._trainable):
            st = self.optimizer.create_state_multi_precision(i, p.data())
            self._states.append(st)
            self._state_flat.extend(_state_leaves(st))
        # place params on the mesh
        shard_params(net, self.mesh, self.rules)
        # a state leaf shards like its parameter when shapes match
        self._state_shardings = []
        for (name, p), st in zip(self._trainable, self._states):
            psh = NamedSharding(self.mesh, self.rules.spec(logical_axes_of(p)))
            repl = NamedSharding(self.mesh, P())
            for l in _state_leaves(st):
                self._state_shardings.append(
                    psh if tuple(l.shape) == tuple(p.shape) else repl)
        for st, sh in zip(self._state_flat, self._state_shardings):
            st._rebind(_mesh_device_put(st.jax, sh))
        self._state_trees = [_flatten_state(st)[1] for st in self._states]
        self._state_counts = [len(_state_leaves(st)) for st in self._states]
        if self._guarded:
            init_scale = (self._loss_scaler.loss_scale
                          if self._loss_scaler is not None else 1.0)
            self._scale_arr = jnp.asarray(init_scale, jnp.float32)
            self._good_arr = jnp.asarray(0, jnp.int32)
        self._compile(data, labels)
        self._built = True
        if self._pending_states is not None:
            self._apply_loaded_states(self._pending_states)
            self._pending_states = None

    # ------------------------------------------------------------------
    def _make_pure(self, n_data):
        net, loss_fn, optimizer = self.net, self.loss, self.optimizer
        trainable, aux = self._trainable, self._aux
        state_trees, state_counts = self._state_trees, self._state_counts

        mesh = self.mesh

        accum = self._grad_accum

        def forward_loss(pvals, aux_now, data_vals, label_vals, k):
            """Loss + updated aux payloads for ONE (micro)batch — a pure
            function of its arguments, re-enterable per scan iteration."""
            _random.push_trace_key(k)
            aux_nds = [p._data for _, p in aux]
            swap_ctx = swap_values(aux_nds, aux_now)
            swap_ctx.__enter__()
            try:
                data = [NDArray(v) for v in data_vals]
                labels = [NDArray(v) for v in label_vals]
                _base.pop_aux_losses()   # discard stale entries (e.g.
                # from the eager shape-settling forward) so the loss
                # only sums aux losses of THIS trace
                # loss runs inside this same trace → tracers may be
                # collected (MoE router aux losses)
                aux_prev = _base.set_aux_collection(True)
                try:
                    with swap_values([p._data for _, p in trainable],
                                     pvals):
                        with _base.training_mode(True):
                            rec = _base.set_recording(False)
                            try:
                                out = net.forward(*data)
                            finally:
                                _base.set_recording(rec)
                        if loss_fn is not None:
                            l = loss_fn(out, *labels)
                        else:
                            l = out
                        lval = l.jax if isinstance(l, NDArray) else l
                        lval = jnp.mean(lval)
                        new_aux = tuple(
                            p._data._data for _, p in aux)
                        return lval, new_aux
                finally:
                    _base.set_aux_collection(aux_prev)
                    _base.pop_aux_losses()  # nothing may outlive the
                    # trace, drained or not
            finally:
                swap_ctx.__exit__(None, None, None)
                _random.pop_trace_key()

        # NOTE: pure() and pure_guarded() below are deliberate near-twins.
        # They are NOT folded into one function driven by constant guard
        # inputs because the unguarded jaxpr must stay byte-identical
        # across this change: it keys the persistent XLA compile cache for
        # every existing unguarded run, and relying on XLA to fold away
        # constant-predicate selects is a bet, not a guarantee.  A fix to
        # the shared step logic (microbatch scan, optimizer state
        # wrapping) must be applied to BOTH.
        def pure(param_vals, aux_vals, state_vals, batch_vals, key, lr, t):
            _random.push_trace_key(key)
            ctx = use_mesh(mesh)
            ctx.__enter__()
            try:
                data_vals = tuple(batch_vals[:n_data])
                label_vals = tuple(batch_vals[n_data:])
                if accum == 1:
                    (loss_val, new_aux), grads = jax.value_and_grad(
                        lambda pv: forward_loss(pv, aux_vals, data_vals,
                                                label_vals, key),
                        has_aux=True)(tuple(param_vals))
                else:
                    # gradient accumulation: scan over microbatches —
                    # activations live for ONE microbatch; grads
                    # accumulate in f32; BN/aux state threads through
                    # the carry like sequential small steps would
                    def split_mb(v):
                        return v.reshape(
                            (accum, v.shape[0] // accum) + v.shape[1:])

                    mb_data = tuple(split_mb(v) for v in data_vals)
                    mb_labels = tuple(split_mb(v) for v in label_vals)
                    keys = jax.random.split(key, accum)

                    def body(carry, xs):
                        aux_c, gacc, lacc = carry
                        k_i, d_i, l_i = xs
                        (lv, aux_n), g = jax.value_and_grad(
                            lambda pv: forward_loss(pv, aux_c, d_i, l_i,
                                                    k_i),
                            has_aux=True)(tuple(param_vals))
                        gacc = tuple(
                            a + b.astype(jnp.float32)
                            for a, b in zip(gacc, g))
                        return (aux_n, gacc,
                                lacc + lv.astype(jnp.float32)), None

                    g0 = tuple(jnp.zeros(v.shape, jnp.float32)
                               for v in param_vals)
                    carry0 = (tuple(aux_vals), g0,
                              jnp.zeros((), jnp.float32))
                    (new_aux, gsum, lsum), _ = jax.lax.scan(
                        body, carry0, (keys, mb_data, mb_labels))
                    grads = tuple(
                        (g / accum).astype(v.dtype)
                        for g, v in zip(gsum, param_vals))
                    loss_val = lsum / accum

                new_params, new_states = [], []
                with optimizer.traced(lr, t):
                    off = 0
                    for i, ((name, p), g) in enumerate(zip(trainable, grads)):
                        w_nd = NDArray(param_vals[i])
                        n = state_counts[i]
                        it = iter(state_vals[off:off + n])
                        st = _wrap_state(state_trees[i], it)
                        off += n
                        optimizer.update_multi_precision(
                            i, w_nd, NDArray(g), st)
                        new_params.append(w_nd._data)
                        new_states.extend(
                            l._data for l in _state_leaves(st))
                return (loss_val, tuple(new_params), tuple(new_aux),
                        tuple(new_states))
            finally:
                ctx.__exit__()
                _random.pop_trace_key()

        if not self._guarded:
            return pure

        has_scaler = self._loss_scaler is not None
        scaler = self._loss_scaler
        clip_norm = self._clip_global_norm

        def pure_guarded(param_vals, aux_vals, state_vals, batch_vals, key,
                         lr, t, scale, good, lpoison, gpoison):
            """The guarded step: loss scaling, NaN/Inf injection splice
            points, global-norm clipping, the in-graph ``all_finite``
            flag, and a ``jnp.where``-masked optimizer update — one
            straight-line XLA program (no ``lax.cond``: both arms of a
            skip are trivially cheap selects, and a single program keeps
            compile count and step time identical to the happy path).
            Mirrors ``pure()`` above — keep shared step logic in sync
            (see the NOTE there for why they are not merged)."""
            _random.push_trace_key(key)
            ctx = use_mesh(mesh)
            ctx.__enter__()
            try:
                data_vals = tuple(batch_vals[:n_data])
                label_vals = tuple(batch_vals[n_data:])

                def scaled_loss(pv, aux_now, d, l, k):
                    lval, aux_n = forward_loss(pv, aux_now, d, l, k)
                    # loss poison splice: lpoison is 0.0 (finite → keep
                    # the real loss) or NaN/Inf from the fault plan
                    lval = jnp.where(jnp.isfinite(lpoison), lval,
                                     lpoison.astype(lval.dtype))
                    out = lval * scale.astype(lval.dtype) \
                        if has_scaler else lval
                    return out, (lval, aux_n)

                if accum == 1:
                    (_slval, (loss_val, new_aux)), grads = \
                        jax.value_and_grad(
                            lambda pv: scaled_loss(pv, aux_vals, data_vals,
                                                   label_vals, key),
                            has_aux=True)(tuple(param_vals))
                else:
                    def split_mb(v):
                        return v.reshape(
                            (accum, v.shape[0] // accum) + v.shape[1:])

                    mb_data = tuple(split_mb(v) for v in data_vals)
                    mb_labels = tuple(split_mb(v) for v in label_vals)
                    keys = jax.random.split(key, accum)

                    def body(carry, xs):
                        aux_c, gacc, lacc = carry
                        k_i, d_i, l_i = xs
                        (_slv, (lv, aux_n)), g = jax.value_and_grad(
                            lambda pv: scaled_loss(pv, aux_c, d_i, l_i,
                                                   k_i),
                            has_aux=True)(tuple(param_vals))
                        gacc = tuple(
                            a + b.astype(jnp.float32)
                            for a, b in zip(gacc, g))
                        return (aux_n, gacc,
                                lacc + lv.astype(jnp.float32)), None

                    g0 = tuple(jnp.zeros(v.shape, jnp.float32)
                               for v in param_vals)
                    carry0 = (tuple(aux_vals), g0,
                              jnp.zeros((), jnp.float32))
                    (new_aux, gsum, lsum), _ = jax.lax.scan(
                        body, carry0, (keys, mb_data, mb_labels))
                    grads = tuple(
                        (g / accum).astype(v.dtype)
                        for g, v in zip(gsum, param_vals))
                    loss_val = lsum / accum

                if has_scaler:       # unscale BEFORE clip/flag/update
                    inv = 1.0 / scale
                    grads = tuple(g * inv.astype(g.dtype) for g in grads)
                # grad poison splice (same contract as lpoison)
                grads = tuple(
                    jnp.where(jnp.isfinite(gpoison), g,
                              gpoison.astype(g.dtype))
                    for g in grads)

                all_finite = jnp.isfinite(loss_val)
                for g in grads:
                    all_finite = all_finite & jnp.all(jnp.isfinite(g))

                if clip_norm is not None:
                    gnorm = jnp.sqrt(sum(
                        jnp.sum(jnp.square(g.astype(jnp.float32)))
                        for g in grads))
                    coef = jnp.minimum(1.0, clip_norm / (gnorm + 1e-6))
                    grads = tuple(g * coef.astype(g.dtype) for g in grads)

                # zero the grads on a bad step so Inf*0 inside the
                # optimizer can't mint fresh NaNs; the where-select on
                # params/aux/state below is what makes the skip
                # bit-identical
                grads = tuple(
                    jnp.where(all_finite, g, jnp.zeros_like(g))
                    for g in grads)

                new_params, new_states = [], []
                with optimizer.traced(lr, t):
                    off = 0
                    for i, ((name, p), g) in enumerate(zip(trainable,
                                                           grads)):
                        w_nd = NDArray(param_vals[i])
                        n = state_counts[i]
                        old_states = state_vals[off:off + n]
                        it = iter(old_states)
                        st = _wrap_state(state_trees[i], it)
                        off += n
                        optimizer.update_multi_precision(
                            i, w_nd, NDArray(g), st)
                        new_params.append(
                            jnp.where(all_finite, w_nd._data,
                                      param_vals[i]))
                        new_states.extend(
                            jnp.where(all_finite, l._data, old)
                            for l, old in zip(_state_leaves(st),
                                              old_states))
                new_aux = tuple(
                    jnp.where(all_finite, a, old)
                    for a, old in zip(new_aux, aux_vals))

                if has_scaler:
                    factor = jnp.float32(scaler._scale_factor)
                    window = jnp.int32(scaler._scale_window)
                    shrunk = jnp.maximum(scale / factor, 1.0)
                    good_ok = good + 1
                    grow = good_ok >= window
                    grown = jnp.where(grow, scale * factor, scale)
                    good_ok = jnp.where(grow, jnp.int32(0), good_ok)
                    new_scale = jnp.where(all_finite, grown, shrunk)
                    new_good = jnp.where(all_finite, good_ok,
                                         jnp.int32(0))
                else:
                    new_scale = scale
                    new_good = jnp.where(all_finite, good + 1,
                                         jnp.int32(0))

                return (loss_val, all_finite, new_scale, new_good,
                        tuple(new_params), tuple(new_aux),
                        tuple(new_states))
            finally:
                ctx.__exit__()
                _random.pop_trace_key()

        return pure_guarded

    # ------------------------------------------------------------------
    def _compile(self, data, labels):
        mesh, rules = self.mesh, self.rules
        pure = self._make_pure(len(data))

        def ns(spec):
            return NamedSharding(mesh, spec)

        param_sh = tuple(ns(rules.spec(logical_axes_of(p)))
                         for _, p in self._trainable)
        aux_sh = tuple(ns(rules.spec(logical_axes_of(p)))
                       for _, p in self._aux)
        state_sh = tuple(self._state_shardings)

        def default_spec(v):
            return batch_spec(v.ndim, 0, self._seq_axis)

        data_sh = tuple(ns(s) for s in (
            self._data_specs or [default_spec(d) for d in data]))
        label_sh = tuple(ns(s) for s in (
            self._label_specs or [default_spec(l) for l in labels]))
        self._batch_shardings = data_sh + label_sh
        scalar = ns(P())

        # donate on accelerators only: on CPU-XLA donation buys nothing
        # (host memory, no in-place MXU update) and combined with the
        # persistent compilation cache it corrupts the heap on cache
        # HITS — deserialized executables mis-handle the aliased
        # buffers (observed: NaN params, GC-time segfaults).  Same
        # gating the serving engine applies to its KV cache donation.
        donate = self._donate and jax.default_backend() != "cpu"
        dargs = (0, 1, 2) if donate else ()
        if self._donate_batch and jax.default_backend() != "cpu":
            # batch buffers are argument 3; donating them lets XLA
            # recycle the prefetcher's freshly-shipped arrays in place
            dargs += (3,)
        if self._guarded:
            # extra traced scalars: loss scale, consecutive-finite
            # counter, and the two poison splice values — runtime
            # inputs, so scale updates and fault injection never
            # recompile (still exactly ONE compiled step function)
            self._step_fn = jax.jit(
                pure,
                in_shardings=(param_sh, aux_sh, state_sh,
                              data_sh + label_sh, scalar, scalar, scalar,
                              scalar, scalar, scalar, scalar),
                out_shardings=(scalar, scalar, scalar, scalar,
                               param_sh, aux_sh, state_sh),
                donate_argnums=dargs)
        else:
            self._step_fn = jax.jit(
                pure,
                in_shardings=(param_sh, aux_sh, state_sh,
                              data_sh + label_sh, scalar, scalar, scalar),
                out_shardings=(scalar, param_sh, aux_sh, state_sh),
                donate_argnums=dargs)

    # ------------------------------------------------------------------
    def build(self, data, labels=()):
        """Settle shapes, shard params and compile WITHOUT stepping —
        params are untouched, so a resume can restore a checkpoint into
        a freshly built trainer before any optimizer update runs
        (ResilientLoop's resume path)."""
        if not isinstance(data, (tuple, list)):
            data = (data,)
        if not isinstance(labels, (tuple, list)):
            labels = (labels,)
        if not self._built:
            self._build(data, labels)
        return self

    def step(self, data, labels=()):
        """Run one full training step.

        Returns the (replicated, lazy) loss NDArray — or, when the
        guardrails are compiled in (``guard_nonfinite`` /
        ``clip_global_norm`` / an attached loss scaler), the pair
        ``(loss, all_finite)``: ``all_finite`` is a lazy boolean
        NDArray that is False iff this step's loss or gradients were
        non-finite, in which case params/aux/optimizer state were left
        bit-identical (the update was a no-op select) and the loss
        scale was shrunk.  Neither return forces a device→host sync;
        callers that don't read the flag pay nothing for it.
        """
        tr = _trace_active()
        if tr is None:              # zero-cost: one global + None check
            return self._step(data, labels)
        src = self._data_source
        if src is None:
            with tr.span("trainer.step",
                         step=self.optimizer.num_update + 1,
                         guarded=self._guarded):
                return self._step(data, labels)
        # per-step input-wait stamp: how long the caller's last batch
        # acquisition blocked on the prefetch ring (0 = fully hidden)
        with tr.span("trainer.step", step=self.optimizer.num_update + 1,
                     guarded=self._guarded,
                     input_wait=round(
                         getattr(src, "last_wait_seconds", 0.0), 6)):
            return self._step(data, labels)

    def _step(self, data, labels=()):
        _inject("trainer.step")
        self._obs_steps.inc()
        if not isinstance(data, (tuple, list)):
            data = (data,)
        if not isinstance(labels, (tuple, list)):
            labels = (labels,)
        if self._grad_accum > 1 and data and \
                data[0].shape[0] % self._grad_accum:
            raise _base.MXNetError(
                f"batch dim {data[0].shape[0]} not divisible by "
                f"grad_accum={self._grad_accum}")
        if not self._built:
            self._build(data, labels)
        opt = self.optimizer
        opt.num_update += 1
        lr = jnp.asarray(opt.learning_rate, jnp.float32)
        t = jnp.asarray(opt.num_update, jnp.int32)
        key = _random.next_key()

        param_vals = tuple(p._data.jax for _, p in self._trainable)
        aux_vals = tuple(p._data.jax for _, p in self._aux)
        state_vals = tuple(l.jax for l in self._state_flat)
        batch_vals = tuple(
            _mesh_device_put(x.jax if isinstance(x, NDArray)
                             else jnp.asarray(x), sh)
            for x, sh in zip(tuple(data) + tuple(labels),
                             self._batch_shardings))

        if self._guarded:
            lp = _poison("trainer.loss_nonfinite")
            gp = _poison("trainer.grad_nonfinite")
            lp = jnp.asarray(0.0 if lp is None else lp, jnp.float32)
            gp = jnp.asarray(0.0 if gp is None else gp, jnp.float32)
            (loss, flag, new_scale, new_good, new_params, new_aux,
             new_states) = self._step_fn(
                param_vals, aux_vals, state_vals, batch_vals, key, lr, t,
                self._scale_arr, self._good_arr, lp, gp)
            self._scale_arr, self._good_arr = new_scale, new_good
        else:
            loss, new_params, new_aux, new_states = self._step_fn(
                param_vals, aux_vals, state_vals, batch_vals, key, lr, t)

        for (_, p), v in zip(self._trainable, new_params):
            p._data._rebind(v)
        for (_, p), v in zip(self._aux, new_aux):
            p._data._rebind(v)
        for l, v in zip(self._state_flat, new_states):
            l._rebind(v)
        if self._guarded:
            return NDArray(loss), NDArray(flag)
        return NDArray(loss)

    # ------------------------------------------------------------------
    @property
    def batch_shardings(self):
        """Target ``NamedSharding`` per flattened ``data + labels``
        array (None before the first ``build()``/``step()``) — what a
        :class:`mxnet_tpu.data.DevicePrefetcher` ships against so the
        hot-path ``device_put`` is a no-op."""
        return getattr(self, "_batch_shardings", None)

    def attach_data_source(self, source):
        """Associate the input pipeline (a ``DevicePrefetcher`` or
        anything with ``stats()``/``last_wait_seconds``) so
        ``stats()['data']`` and the per-step ``trainer.step`` span
        carry the input-wait facts.  Returns ``source`` for chaining."""
        self._data_source = source
        return source

    def stats(self) -> dict:
        """Point-in-time trainer facts (the engine-``stats()`` shape):
        step counter plus a ``data`` section from the attached input
        pipeline when one is present."""
        out = {"num_update": int(self.optimizer.num_update),
               "built": self._built,
               "guarded": self._guarded}
        src = self._data_source
        if src is not None and hasattr(src, "stats"):
            out["data"] = src.stats()
        return out

    @property
    def learning_rate(self):
        return self.optimizer.learning_rate

    def set_learning_rate(self, lr):
        self.optimizer.set_learning_rate(lr)

    def save_states(self, fname):
        from ..ndarray import array as _nd_array
        from ..utils.serialization import save
        if not self._built:
            raise _base.MXNetError(
                "save_states before the first step(): optimizer states do "
                "not exist yet (nothing to save)")
        data = {"num_update": _nd_array([self.optimizer.num_update],
                                        dtype="int64")}
        if self._guarded:
            data["loss_scale"] = _nd_array([self.loss_scale],
                                           dtype="float32")
            data["good_steps"] = _nd_array([int(self._good_arr)],
                                           dtype="int64")
        for i, st in enumerate(self._states):
            for j, l in enumerate(_state_leaves(st)):
                data[f"state_{i}_{j}"] = l
        save(fname, data)

    def load_states(self, fname):
        from ..utils.serialization import load
        loaded = load(fname)
        if not self._built:
            # states don't exist until the first step; apply after _build
            self._pending_states = loaded
            return
        self._apply_loaded_states(loaded)

    # ------------------------------------------------------- flat state dict
    def state_dict(self) -> Dict[str, NDArray]:
        """The trainer's whole restorable state as a FLAT ``{key:
        NDArray}`` dict (params, aux, optimizer-state leaves, step
        counter) — the unit :class:`~mxnet_tpu.resilience.ResilientLoop`
        commits through its atomic checkpointer and the portable
        counterpart of the orbax tree in :meth:`save_checkpoint`.

        Keys are POSITIONAL (``param:0``, ``aux:0``, ``state:0``):
        parameter *names* carry a process-global counter, so a resumed
        process (whose fresh net may count from a different base) could
        never match them; collection order is deterministic for a given
        model, which is exactly the resume contract.  Shapes are
        verified on load."""
        from ..ndarray import array as _nd_array
        if not self._built:
            raise _base.MXNetError(
                "state_dict before build: run build()/step() first so "
                "params and optimizer states exist")
        out: Dict[str, NDArray] = {
            "meta:num_update": _nd_array([self.optimizer.num_update],
                                         dtype="int64")}
        if self._guarded:
            # guard state rides the checkpoint so a resume/rewind also
            # restores the dynamic loss scale and its grow counter
            out["meta:loss_scale"] = _nd_array([self.loss_scale],
                                               dtype="float32")
            out["meta:good_steps"] = _nd_array([int(self._good_arr)],
                                               dtype="int64")
        for i, (_n, p) in enumerate(self._trainable):
            out[f"param:{i}"] = p._data
        for i, (_n, p) in enumerate(self._aux):
            out[f"aux:{i}"] = p._data
        for i, l in enumerate(self._state_flat):
            out[f"state:{i}"] = l
        return out

    def load_state_dict(self, d: Dict[str, NDArray]):
        """Inverse of :meth:`state_dict`: rebind every leaf onto its live
        mesh sharding.  Missing keys or mismatched shapes are an error
        (a foreign/corrupt checkpoint — refuse, don't half-load)."""
        if not self._built:
            raise _base.MXNetError(
                "load_state_dict needs the trainer built — call "
                "build() on example data first (shapes/shardings "
                "must exist)")
        want = ([f"param:{i}" for i in range(len(self._trainable))]
                + [f"aux:{i}" for i in range(len(self._aux))]
                + [f"state:{i}" for i in range(len(self._state_flat))]
                + ["meta:num_update"])
        missing = [k for k in want if k not in d]
        if missing:
            raise _base.MXNetError(
                f"state dict is missing {len(missing)} keys "
                f"(e.g. {missing[:3]}) — not a checkpoint of this "
                "trainer/model")

        def _check(key, have, want_shape, name):
            if tuple(have.shape) != tuple(want_shape):
                raise _base.MXNetError(
                    f"state dict {key} ({name}) has shape "
                    f"{tuple(have.shape)}, expected {tuple(want_shape)} "
                    "— checkpoint of a different model")

        for i, (n, p) in enumerate(self._trainable):
            _check(f"param:{i}", d[f"param:{i}"], p.shape, n)
        for i, (n, p) in enumerate(self._aux):
            _check(f"aux:{i}", d[f"aux:{i}"], p.shape, n)
        for i, l in enumerate(self._state_flat):
            _check(f"state:{i}", d[f"state:{i}"], l.shape, "opt state")
        for i, (_n, p) in enumerate(self._trainable):
            sh = NamedSharding(self.mesh, self.rules.spec(logical_axes_of(p)))
            p._data._rebind(_mesh_device_put(d[f"param:{i}"].jax, sh))
        for i, (_n, p) in enumerate(self._aux):
            sh = NamedSharding(self.mesh, self.rules.spec(logical_axes_of(p)))
            p._data._rebind(_mesh_device_put(d[f"aux:{i}"].jax, sh))
        for i, l in enumerate(self._state_flat):
            l._rebind(_mesh_device_put(d[f"state:{i}"].jax,
                                       self._state_shardings[i]))
        self.optimizer.num_update = int(
            d["meta:num_update"].asnumpy()[0])
        if self._guarded:
            # optional (a checkpoint from an unguarded run lacks them)
            if "meta:loss_scale" in d:
                self._scale_arr = jnp.asarray(
                    float(d["meta:loss_scale"].asnumpy()[0]), jnp.float32)
            if "meta:good_steps" in d:
                self._good_arr = jnp.asarray(
                    int(d["meta:good_steps"].asnumpy()[0]), jnp.int32)

    # -------------------------------------------------- sharded checkpoints
    def _checkpoint_tree(self):
        return {
            "params": {n: p._data for n, p in self._trainable},
            "aux": {n: p._data for n, p in self._aux},
            "states": {f"s{i}": l for i, l in enumerate(self._state_flat)},
        }

    def save_checkpoint(self, directory, step: int, async_save=True,
                        max_to_keep=5):
        """Async sharded checkpoint (orbax): params + aux + optimizer states
        + step counter; each host writes only its shards.  One manager is
        cached per directory (so periodic saves share async machinery and
        max_to_keep GC never races an in-flight write); returns it so
        callers can `wait_until_finished` before exit."""
        import os
        from ..utils.checkpoint import CheckpointManager
        if not self._built:
            raise _base.MXNetError("save_checkpoint before first step()")
        key = os.path.abspath(str(directory))
        cached = self._ckpt_managers.get(key)
        if cached is not None and cached[1] != (max_to_keep, async_save):
            cached[0].wait_until_finished()
            cached[0].close()
            cached = None
        if cached is None:
            m = CheckpointManager(directory, max_to_keep=max_to_keep,
                                  async_save=async_save)
            self._ckpt_managers[key] = (m, (max_to_keep, async_save))
        else:
            m = cached[0]
        tree = self._checkpoint_tree()
        tree["num_update"] = jnp.asarray(self.optimizer.num_update, jnp.int32)
        if self._guarded:
            # the guard schedule is restorable state on EVERY checkpoint
            # surface (state_dict carries meta:loss_scale/good_steps):
            # resuming with a reset scale would overflow-skip until it
            # re-shrinks
            tree["loss_scale"] = jnp.asarray(self._scale_arr, jnp.float32)
            tree["good_steps"] = jnp.asarray(self._good_arr, jnp.int32)
        m.save(step, tree)
        return m

    def load_checkpoint(self, directory, step=None):
        """Restore a sharded checkpoint with the live NamedShardings."""
        import os
        from ..utils.checkpoint import CheckpointManager
        if not self._built:
            raise _base.MXNetError(
                "load_checkpoint needs the trainer built — run one step() "
                "on example data first (shapes/shardings must exist)")
        # drain any in-flight async save to this directory first, else the
        # restore silently lands on the previous step
        cached = self._ckpt_managers.get(os.path.abspath(str(directory)))
        if cached is not None:
            cached[0].wait_until_finished()
        like = self._checkpoint_tree()
        like["num_update"] = jnp.asarray(0, jnp.int32)
        if self._guarded:
            like["loss_scale"] = jnp.asarray(0.0, jnp.float32)
            like["good_steps"] = jnp.asarray(0, jnp.int32)
        m = CheckpointManager(directory, async_save=False)
        try:
            restored = m.restore(step, like=like)
        finally:
            m.close()
        for n, p in self._trainable:
            p._data._rebind(restored["params"][n])
        for n, p in self._aux:
            p._data._rebind(restored["aux"][n])
        for i, l in enumerate(self._state_flat):
            l._rebind(restored["states"][f"s{i}"])
        self.optimizer.num_update = int(restored["num_update"])
        if self._guarded:
            self._scale_arr = jnp.asarray(float(restored["loss_scale"]),
                                          jnp.float32)
            self._good_arr = jnp.asarray(int(restored["good_steps"]),
                                         jnp.int32)

    def _apply_loaded_states(self, loaded):
        if "num_update" in loaded:
            self.optimizer.num_update = int(loaded["num_update"].asnumpy()[0])
        if self._guarded:
            if "loss_scale" in loaded:
                self._scale_arr = jnp.asarray(
                    float(loaded["loss_scale"].asnumpy()[0]), jnp.float32)
            if "good_steps" in loaded:
                self._good_arr = jnp.asarray(
                    int(loaded["good_steps"].asnumpy()[0]), jnp.int32)
        flat_idx = 0
        for i, st in enumerate(self._states):
            for j, l in enumerate(_state_leaves(st)):
                l._rebind(_mesh_device_put(loaded[f"state_{i}_{j}"].jax,
                                         self._state_shardings[flat_idx]))
                flat_idx += 1
