"""Pipeline parallelism over the ``pp`` mesh axis (GPipe schedule).

Capability add over the reference (SURVEY.md §2.4: "PP: none" — MXNet's
only model parallelism was manual ``group2ctx`` device placement with
executor-inserted copies).  TPU-first design: the model's repeated trunk
is expressed as *stacked* per-layer parameters (leading dim = layers);
under ``pp`` the stack splits into contiguous stages, each device runs its
stage inside ``shard_map``, and microbatches flow stage-to-stage through
``jax.lax.ppermute`` (XLA lowers to ICI neighbor sends).  The schedule is
a ``lax.scan`` over ``M + P - 1`` ticks — one stage application per tick
per device — so utilization is the standard GPipe M/(M+P-1) and the
backward pass (derived by AD through scan+ppermute) is the reverse
pipeline.
"""
from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .mesh import axis_size, current_mesh

__all__ = ["gpipe"]


def _stage_slice(tree):
    return jax.tree_util.tree_map(lambda a: a[0], tree)


def gpipe(stage_fn: Callable[[Any, jax.Array], jax.Array], params, x,
          *, num_microbatches: int, mesh=None, axis: str = "pp",
          batch_axis: str = "dp"):
    """Run ``x`` through ``P`` pipeline stages with GPipe microbatching.

    stage_fn(stage_params, x_mb) -> y_mb, same shape as ``x_mb``.
    ``params``: pytree whose leaves all have leading dim ``P`` (stage
    count = size of the ``axis`` mesh axis); stage ``i`` uses leaf[i].
    ``x``: (B, ...) with B divisible by num_microbatches (and the
    microbatch count should be >= P for reasonable utilization).
    Batch stays sharded over ``batch_axis`` so dp x pp compose.
    """
    mesh = mesh or current_mesh()
    p = axis_size(mesh, axis) if mesh is not None else 1
    if p == 1:
        return stage_fn(_stage_slice(params), x)
    m = num_microbatches
    b = x.shape[0]
    dpn = axis_size(mesh, batch_axis)
    if b % dpn or (b // dpn) % m:
        raise ValueError(
            f"per-{batch_axis}-shard batch {b}//{dpn} must be divisible "
            f"by num_microbatches={m}")

    def body(params, xl):
        stage = jax.lax.axis_index(axis)
        local = _stage_slice(params)
        bl = xl.shape[0]
        micro = xl.reshape(m, bl // m, *xl.shape[1:])
        outs0 = jnp.zeros_like(micro)
        recv0 = jnp.zeros_like(micro[0])
        perm = [(i, i + 1) for i in range(p - 1)]

        def tick(carry, step):
            recv, outs = carry
            mb = jnp.clip(step, 0, m - 1)
            x_in = jnp.where(
                stage == 0,
                jax.lax.dynamic_index_in_dim(micro, mb, 0, keepdims=False),
                recv)
            y = stage_fn(local, x_in)
            out_idx = jnp.clip(step - (p - 1), 0, m - 1)
            valid = (stage == p - 1) & (step >= p - 1)
            cur = jax.lax.dynamic_index_in_dim(outs, out_idx, 0,
                                               keepdims=False)
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, jnp.where(valid, y, cur), out_idx, 0)
            send = jax.lax.ppermute(y, axis, perm)
            return (send, outs), None

        (_, outs), _ = jax.lax.scan(tick, (recv0, outs0),
                                    jnp.arange(m + p - 1))
        # only the last stage holds real outputs; broadcast over the ring
        outs = jax.lax.psum(
            jnp.where(stage == p - 1, outs, jnp.zeros_like(outs)), axis)
        return outs.reshape(xl.shape)

    in_spec_p = jax.tree_util.tree_map(lambda _: P(axis), params)
    x_spec = P(batch_axis, *([None] * (x.ndim - 1)))
    f = jax.shard_map(body, mesh=mesh,
                      in_specs=(in_spec_p, x_spec), out_specs=x_spec,
                      check_vma=False)
    if not isinstance(x, jax.core.Tracer):
        from jax.sharding import NamedSharding
        params = jax.tree_util.tree_map(
            lambda a: jax.device_put(a, NamedSharding(mesh, P(axis))),
            params)
        x = jax.device_put(x, NamedSharding(mesh, x_spec))
    return f(params, x)
