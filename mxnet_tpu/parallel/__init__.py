"""mxnet_tpu.parallel — SPMD parallelism over the TPU device mesh.

TPU-native replacement for MXNet's distributed stack (SURVEY.md §2.4):
context lists → named :class:`jax.sharding.Mesh`; KVStore comm backends →
XLA collectives inserted by GSPMD; plus the strategies MXNet never had
(tensor/sequence/pipeline/expert parallel) as first-class axes.
"""
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .distributed import barrier, init_distributed, num_workers, rank
from .mesh import AXES, axis_size, current_mesh, make_mesh, use_mesh
from .pipeline import gpipe
from .sharding import (DEFAULT_RULES, ShardingRules, annotate, batch_spec,
                       divisible_spec, global_batch_sharding,
                       logical_axes_of, param_sharding, shard_params)
from .trainer import ShardedTrainer

__all__ = [
    "AXES", "Mesh", "NamedSharding", "PartitionSpec", "ShardingRules",
    "ShardedTrainer", "annotate", "axis_size", "barrier", "batch_spec",
    "current_mesh", "divisible_spec", "global_batch_sharding", "gpipe",
    "init_distributed",
    "logical_axes_of",
    "make_mesh", "num_workers", "param_sharding", "rank", "shard_params",
    "use_mesh", "with_sharding_constraint", "DEFAULT_RULES",
]


def with_sharding_constraint(x, *logical_axes, mesh=None, rules=None):
    """Pin an activation's layout inside a traced computation.

    Models call this to mark e.g. ``(batch, seq, embed)`` activations as
    ``("dp", "sp", None)`` so GSPMD keeps sequence parallelism instead of
    gathering.  Accepts NDArray or jax.Array; no-op when no mesh is active.
    """
    import jax as _jax

    from ..ndarray import NDArray as _ND
    mesh = mesh or current_mesh()
    if mesh is None:
        return x
    val = x.jax if isinstance(x, _ND) else x
    if not isinstance(val, _jax.core.Tracer):
        return x  # eager: layout hints only matter under GSPMD tracing
    rules = rules or ShardingRules()
    spec = rules.spec(logical_axes)
    # inside a shard_map body the manual axes are already local — drop
    # them from the constraint (constraining on a manual axis is an error)
    try:
        manual = set(_jax.sharding.get_abstract_mesh().manual_axes)
    except Exception:
        manual = set()
    if manual:
        spec = PartitionSpec(
            *[None if (a in manual) else a for a in spec])
        if all(a is None for a in spec):
            return x
    out = _jax.lax.with_sharding_constraint(
        val, NamedSharding(mesh, spec))
    return _ND(out) if isinstance(x, _ND) else out
