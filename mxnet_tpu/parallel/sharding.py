"""Logical-axis sharding rules (TPU-native parameter placement).

MXNet has no parameter sharding (params are replicated per context by
``Trainer``/KVStore broadcast — src/kvstore/comm.h Broadcast).  On TPU,
placement is the performance model, so parameters carry *logical* axis names
("embed", "mlp", "heads", "vocab", …) and a rules table maps logical axes →
mesh axes (the flax/t5x partitioning idiom).  Replication is just the empty
mapping, so data-parallel MXNet semantics fall out as the default.
"""
from __future__ import annotations

import functools as _functools
from typing import Dict, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import base as _base

# Default logical→mesh mapping (Megatron-style TP + sequence axis).
DEFAULT_RULES: Dict[str, Optional[str]] = {
    "batch": "dp",
    "layers": "pp",
    "vocab": "tp",
    "embed": None,
    "heads": "tp",
    "kv": None,
    "mlp": "tp",
    "expert": "ep",
    "seq": "sp",
    "norm": None,
}


class ShardingRules(dict):
    """dict logical-axis-name → mesh-axis-name (or None = replicate)."""

    def __init__(self, rules: Optional[Dict[str, Optional[str]]] = None,
                 **overrides):
        super().__init__(DEFAULT_RULES)
        if rules:
            self.update(rules)
        self.update(overrides)

    def spec(self, logical_axes: Optional[Sequence[Optional[str]]]) -> P:
        """PartitionSpec for a parameter annotated with logical axes."""
        if not logical_axes:
            return P()
        return P(*[self.get(a) if a is not None else None
                   for a in logical_axes])


def annotate(param, *logical_axes):
    """Attach logical axis names to a Parameter (one per dimension)."""
    param._logical_axes = tuple(logical_axes)
    return param


def logical_axes_of(param) -> Optional[Tuple[Optional[str], ...]]:
    return getattr(param, "_logical_axes", None)


def mesh_device_put(value, sharding):
    """``jax.device_put`` that also works onto MULTI-PROCESS meshes.

    A process-local committed array cannot be device_put to
    non-addressable devices (no raw DCN transport on the CPU/test
    backends), so it hops through host memory — every process holds the
    full value and materializes its own shards (the standard multihost
    ingest pattern).  An already-GLOBAL array cannot be fetched to host
    either; it is resharded inside a compiled identity whose collectives
    ride the coordination service/ICI/DCN."""
    if isinstance(value, jax.Array) and \
            not getattr(sharding, "is_fully_addressable", True):
        if getattr(value, "sharding", None) == sharding:
            return value
        if value.is_fully_addressable:
            import numpy as onp
            value = onp.asarray(value)
        else:
            return _reshard_fn(sharding)(value)
    return jax.device_put(value, sharding)


@_functools.lru_cache(maxsize=None)
def _reshard_fn(sharding):
    """One cached compiled identity per target sharding (jax.jit caches by
    function identity — a fresh lambda per call would recompile every
    state-leaf reshard)."""
    return jax.jit(lambda x: x, out_shardings=sharding)


def param_sharding(param, mesh: Mesh,
                   rules: Optional[ShardingRules] = None) -> NamedSharding:
    rules = rules or ShardingRules()
    return NamedSharding(mesh, rules.spec(logical_axes_of(param)))


def divisible_spec(shape, logical_axes, mesh: Mesh, mapping) -> P:
    """PartitionSpec mapping each logical axis through ``mapping``
    (logical name → mesh axis name), REPLICATING any dimension whose
    size does not divide its mesh axis — the pragmatic t5x-style
    fallback a *serving* mesh wants: an odd-sized vocab table (97 on a
    2-way mesh) replicates instead of erroring, while the axes that
    MUST shard evenly (the KV head dimension) are validated separately
    by the caller (`InferenceEngine`'s typed construction checks,
    docs/serving.md "Sharded decode")."""
    from .mesh import axis_size
    spec = []
    axes = tuple(logical_axes or ())
    for i, dim in enumerate(shape):
        a = axes[i] if i < len(axes) else None
        m = mapping.get(a) if a is not None else None
        if m is not None:
            sz = axis_size(mesh, m)
            if sz > 1 and dim % sz == 0:
                spec.append(m)
                continue
        spec.append(None)
    return P(*spec)


def shard_params(block, mesh: Mesh, rules: Optional[ShardingRules] = None):
    """Place every initialized parameter of ``block`` onto the mesh per the
    rules (replacing KVStore broadcast: parity src/kvstore/comm.h
    Comm::Broadcast — replication is now a NamedSharding, sharding is free).
    """
    rules = rules or ShardingRules()
    for _, p in block.collect_params().items():
        if p._data is None:
            continue
        sh = NamedSharding(mesh, rules.spec(logical_axes_of(p)))
        p._sharding = sh
        p._data._rebind(mesh_device_put(p._data.jax, sh))
    return block


def batch_spec(ndim: int, batch_axis: int = 0, seq_axis: Optional[int] = None
               ) -> P:
    """PartitionSpec for an input batch: batch dim over dp, optional
    sequence dim over sp, rest replicated."""
    axes: list = [None] * ndim
    axes[batch_axis] = "dp"
    if seq_axis is not None:
        axes[seq_axis] = "sp"
    return P(*axes)


def global_batch_sharding(mesh: Mesh, ndim: int, batch_axis: int = 0,
                          seq_axis: Optional[int] = None) -> NamedSharding:
    """The ``NamedSharding`` an input batch lands under — the one-liner
    the data pipeline needs: feed it to ``ShardedLoader`` /
    ``DevicePrefetcher`` and to the trainer's ``data_specs`` and both
    sides agree on placement by construction."""
    return NamedSharding(mesh, batch_spec(ndim, batch_axis, seq_axis))
