"""Multi-host bring-up (parity: ps-lite Postoffice rendezvous +
kvstore_dist roles, SURVEY.md §2.4/§3.5).

The reference rendezvouses scheduler/server/worker processes over ZMQ
with DMLC_* env; here every process is a worker and rendezvous is the
JAX coordination service — after :func:`init_distributed`,
``jax.devices()`` spans all hosts and the SAME mesh/psum code paths
(mxnet_tpu.parallel) scale from one chip to a pod, collectives riding
ICI within a slice and DCN across slices.
"""
from __future__ import annotations

import os
from typing import Optional

import jax

__all__ = ["init_distributed", "rank", "num_workers", "barrier"]

_initialized = False


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Join the job's coordination service (idempotent).

    Arguments default from the env set by tools/launch.py
    (MXNET_TPU_COORD_ADDR/RANK/NPROCS); on Cloud TPU pods all three stay
    None and the TPU metadata provides topology.
    """
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or \
        os.environ.get("MXNET_TPU_COORD_ADDR")
    if num_processes is None and os.environ.get("MXNET_TPU_NPROCS"):
        num_processes = int(os.environ["MXNET_TPU_NPROCS"])
    if process_id is None and os.environ.get("MXNET_TPU_RANK"):
        process_id = int(os.environ["MXNET_TPU_RANK"])
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes, process_id=process_id)
    _initialized = True


def rank() -> int:
    """This process's index (parity: kvstore.rank)."""
    try:
        return jax.process_index()
    except RuntimeError:
        return 0


def num_workers() -> int:
    """Total processes (parity: kvstore.num_workers)."""
    try:
        return jax.process_count()
    except RuntimeError:
        return 1


def barrier(name: str = "mxnet_tpu_barrier") -> None:
    """Block until every process reaches this point (parity: ps-lite
    Postoffice::Barrier) — a tiny psum across all devices."""
    import jax.numpy as jnp
    v = jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")(
        jnp.ones((jax.local_device_count(),)))
    v.block_until_ready()
