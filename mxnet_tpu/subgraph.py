"""Subgraph partitioning API (parity: src/operator/subgraph/
subgraph_property.h + build_subgraph.cc + the ``optimize_for`` backend
registry, SURVEY.md §2.3).

Upstream, a registered ``SubgraphProperty`` matches op patterns in the
NNVM graph and replaces them with fused super-ops (oneDNN conv+bn+relu,
TensorRT engines).  TPU-native: XLA already performs pointwise/conv
fusion, so the surviving value of the API is **semantic** graph rewrites
the compiler cannot do — folding BatchNorm statistics into convolution
weights for inference, swapping layers for INT8 equivalents — expressed
as block-tree (and Symbol-DAG) rewriters behind the same
``SubgraphProperty``/``optimize_for(backend)`` surface.

Built-in backends:
- ``"FUSE_BN"``: fold inference-mode BatchNorm into the preceding
  Conv2D/Dense inside HybridSequential chains (conv+bn+relu row of
  src/operator/subgraph/mkldnn/mkldnn_conv_property.h, done as weight
  algebra instead of a fused kernel).
- ``"INT8"``: delegate to contrib.quantization.quantize_net (the
  quantization subgraph backend).
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

import numpy as onp

from . import base as _base

__all__ = ["SubgraphProperty", "register_backend", "list_backends",
           "optimize_for"]

_BACKENDS: Dict[str, "SubgraphProperty"] = {}


class SubgraphProperty:
    """A named graph-rewrite backend (parity: SubgraphProperty).

    Subclasses implement :meth:`apply_block` (Gluon block tree rewrite)
    and/or :meth:`apply_symbol` (Symbol DAG rewrite) and register with
    :func:`register_backend`.
    """

    name: str = ""

    def apply_block(self, net, **kwargs):
        return net

    def apply_symbol(self, sym, **kwargs):
        raise _base.MXNetError(
            f"backend {self.name or type(self).__name__!r} implements no "
            "Symbol rewrite — apply it to the Gluon block instead")


def register_backend(prop: SubgraphProperty, name: Optional[str] = None):
    """Parity: MXNET_REGISTER_SUBGRAPH_BACKEND/PROPERTY."""
    key = (name or prop.name).upper()
    if not key:
        raise _base.MXNetError("subgraph backend needs a name")
    _BACKENDS[key] = prop
    return prop


def list_backends():
    return sorted(_BACKENDS)


def get_backend(name: str) -> SubgraphProperty:
    key = str(name).upper()
    if key not in _BACKENDS:
        raise _base.MXNetError(
            f"unknown optimize_for backend {name!r}; registered: "
            f"{list_backends()}")
    return _BACKENDS[key]


def optimize_for(net_or_sym, backend, **kwargs):
    """Apply a registered backend to a Gluon block or Symbol."""
    prop = get_backend(backend)
    from .symbol import Symbol
    if isinstance(net_or_sym, Symbol):
        return prop.apply_symbol(net_or_sym, **kwargs)
    out = prop.apply_block(net_or_sym, **kwargs)
    _clear_cached_ops(out)
    return out


def _clear_cached_ops(block):
    """Invalidate every CachedOp in the tree: a rewrite that mutates
    params/children must not let an already-hybridized net replay its
    stale pre-rewrite trace."""
    if hasattr(block, "_clear_cached_op"):
        block._clear_cached_op()
    for child in getattr(block, "_children", {}).values():
        _clear_cached_ops(child)


# ------------------------------------------------------------ FUSE_BN

def _fold_conv_bn(conv, bn):
    """Fold BN inference statistics into conv weight/bias in place."""
    w = conv.weight.data().asnumpy()
    gamma = bn.gamma.data().asnumpy() if bn.gamma is not None else \
        onp.ones(w.shape[0], onp.float32)
    beta = bn.beta.data().asnumpy() if bn.beta is not None else \
        onp.zeros(w.shape[0], onp.float32)
    mean = bn.running_mean.data().asnumpy()
    var = bn.running_var.data().asnumpy()
    eps = bn._eps
    scale = gamma / onp.sqrt(var + eps)
    w2 = w * scale.reshape((-1,) + (1,) * (w.ndim - 1))
    b = conv.bias.data().asnumpy() if conv.bias is not None else \
        onp.zeros(w.shape[0], onp.float32)
    b2 = (b - mean) * scale + beta
    from .ndarray import array as nd_array
    conv.weight.set_data(nd_array(w2.astype(w.dtype)))
    if conv.bias is not None:
        conv.bias.set_data(nd_array(b2.astype(onp.float32)))
        return conv
    # conv had no bias: grow one (a fresh Parameter bound to the block)
    bias = conv.params.get("bias", shape=(w.shape[0],), init="zeros")
    bias.set_data(nd_array(b2.astype(onp.float32)))
    conv.bias = bias
    return conv


def _make_identity():
    """nn.Identity stand-in for a folded-away BatchNorm (keeps
    collect_params / children walks working)."""
    from .gluon.nn import Identity
    return Identity()


class FuseBNProperty(SubgraphProperty):
    """Conv2D/Dense + BatchNorm folding inside HybridSequential chains."""

    name = "FUSE_BN"

    def apply_block(self, net, **kwargs):
        from .gluon.nn import (BatchNorm, Conv2D, Dense,
                               HybridSequential)

        def walk(block):
            if isinstance(block, HybridSequential):
                kids = list(block._children.items())
                for (n1, c1), (n2, c2) in zip(kids, kids[1:]):
                    if isinstance(c1, (Conv2D, Dense)) \
                            and isinstance(c2, BatchNorm) \
                            and getattr(c1, "_activation", None) is None \
                            and c1.weight._data is not None \
                            and getattr(c2, "running_mean", None) is not None \
                            and c2.running_mean._data is not None:
                        # (a fused activation on c1 would make this
                        # BN(act(conv(x))) — not foldable weight algebra)
                        _fold_conv_bn(c1, c2)
                        ident = _make_identity()
                        block._children[n2] = ident
                        if getattr(block, n2, None) is c2:
                            setattr(block, n2, ident)
            for child in list(block._children.values()):
                if hasattr(child, "_children"):
                    walk(child)
            return block

        return walk(net)


register_backend(FuseBNProperty())


# --------------------------------------------------------------- INT8

class Int8Property(SubgraphProperty):
    """Quantization as a subgraph backend (parity: the quantization pass
    run through optimize_for on oneDNN)."""

    name = "INT8"

    def apply_block(self, net, calib_data=None, calib_mode="naive",
                    exclude_layers=None, **kwargs):
        from .contrib.quantization import quantize_net
        return quantize_net(net, calib_data=calib_data,
                            calib_mode=calib_mode,
                            exclude_layers=exclude_layers)


register_backend(Int8Property())
