"""Evaluation metrics (parity: python/mxnet/metric.py)."""
from __future__ import annotations

import math
from typing import List, Optional

import numpy as onp

from . import base as _base
from .ndarray import NDArray

_registry = _base.registry("metric")
register = _registry.register

__all__ = ["EvalMetric", "Accuracy", "TopKAccuracy", "F1", "MCC", "MAE",
           "MSE", "RMSE", "CrossEntropy", "Perplexity", "PearsonCorrelation",
           "Loss", "CompositeEvalMetric", "CustomMetric", "create", "np"]


def _as_numpy(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return onp.asarray(x)


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0
        self.global_num_inst = 0
        self.global_sum_metric = 0.0

    def reset_local(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        raise NotImplementedError

    def _update(self, metric, n=1):
        self.sum_metric += metric
        self.num_inst += n
        self.global_sum_metric += metric
        self.global_num_inst += n

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_global(self):
        if self.global_num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.global_sum_metric / self.global_num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names]
        else:
            label = list(label.values())
        self.update(label, pred)

    def __str__(self):
        return f"EvalMetric: {dict([self.get()])}"


@register("acc")
@register()
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", **kwargs):
        super().__init__(name, **kwargs)
        self.axis = axis

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            pred = _as_numpy(pred)
            label = _as_numpy(label)
            if pred.ndim > label.ndim:
                pred = pred.argmax(axis=self.axis)
            pred = pred.astype(onp.int64).flatten()
            label = label.astype(onp.int64).flatten()
            self._update((pred == label).sum(), len(label))


@register("top_k_accuracy")
@register()
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", **kwargs):
        super().__init__(f"{name}_{top_k}", **kwargs)
        self.top_k = top_k

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            pred = _as_numpy(pred)
            label = _as_numpy(label).astype(onp.int64)
            topk = onp.argsort(pred, axis=-1)[:, -self.top_k:]
            hit = (topk == label[:, None]).any(axis=-1)
            self._update(hit.sum(), len(label))


@register()
class F1(EvalMetric):
    def __init__(self, name="f1", average="macro", **kwargs):
        super().__init__(name, **kwargs)
        self.average = average
        self._tp = self._fp = self._fn = 0

    def reset(self):
        super().reset()
        self._tp = self._fp = self._fn = 0

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            pred = _as_numpy(pred)
            label = _as_numpy(label).astype(onp.int64).flatten()
            if pred.ndim > 1 and pred.shape[-1] > 1:
                pred = pred.argmax(axis=-1)
            else:
                pred = (pred.flatten() > 0.5).astype(onp.int64)
            pred = pred.flatten()
            self._tp += int(((pred == 1) & (label == 1)).sum())
            self._fp += int(((pred == 1) & (label == 0)).sum())
            self._fn += int(((pred == 0) & (label == 1)).sum())
            prec = self._tp / max(self._tp + self._fp, 1)
            rec = self._tp / max(self._tp + self._fn, 1)
            f1 = 2 * prec * rec / max(prec + rec, 1e-12)
            self.sum_metric = f1
            self.num_inst = 1


@register()
class MCC(EvalMetric):
    def __init__(self, name="mcc", **kwargs):
        super().__init__(name, **kwargs)
        self._cm = onp.zeros((2, 2))

    def reset(self):
        super().reset()
        self._cm = onp.zeros((2, 2))

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            pred = _as_numpy(pred)
            label = _as_numpy(label).astype(onp.int64).flatten()
            if pred.ndim > 1 and pred.shape[-1] > 1:
                pred = pred.argmax(axis=-1)
            else:
                pred = (pred.flatten() > 0.5).astype(onp.int64)
            for t, p in zip(label, pred.flatten()):
                self._cm[t, p] += 1
            tn, fp = self._cm[0]
            fn, tp = self._cm[1]
            denom = math.sqrt((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn))
            self.sum_metric = ((tp * tn - fp * fn) / denom) if denom else 0.0
            self.num_inst = 1


@register()
class MAE(EvalMetric):
    def __init__(self, name="mae", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            self._update(onp.abs(label - pred.reshape(label.shape)).mean()
                         * len(label), len(label))


@register()
class MSE(EvalMetric):
    def __init__(self, name="mse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            self._update(((label - pred.reshape(label.shape)) ** 2).mean()
                         * len(label), len(label))


@register()
class RMSE(MSE):
    def __init__(self, name="rmse", **kwargs):
        super().__init__(name, **kwargs)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.sqrt(self.sum_metric / self.num_inst))


@register("ce")
@register()
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", **kwargs):
        super().__init__(name, **kwargs)
        self.eps = eps

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).astype(onp.int64).flatten()
            pred = _as_numpy(pred)
            prob = pred[onp.arange(len(label)), label]
            self._update((-onp.log(prob + self.eps)).sum(), len(label))


@register()
class Perplexity(CrossEntropy):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 **kwargs):
        super().__init__(name=name, **kwargs)
        self.ignore_label = ignore_label

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).astype(onp.int64).flatten()
            pred = _as_numpy(pred).reshape(len(label), -1)
            mask = onp.ones_like(label, dtype=bool)
            if self.ignore_label is not None:
                mask = label != self.ignore_label
            prob = pred[onp.arange(len(label)), label]
            self._update((-onp.log(prob[mask] + self.eps)).sum(),
                         int(mask.sum()))

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@register()
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", **kwargs):
        super().__init__(name, **kwargs)
        self._labels = []
        self._preds = []

    def reset(self):
        super().reset()
        self._labels, self._preds = [], []

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            self._labels.append(_as_numpy(label).flatten())
            self._preds.append(_as_numpy(pred).flatten())
        l = onp.concatenate(self._labels)
        p = onp.concatenate(self._preds)
        self.sum_metric = float(onp.corrcoef(l, p)[0, 1])
        self.num_inst = 1


@register()
class Loss(EvalMetric):
    def __init__(self, name="loss", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, _, preds):
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for pred in preds:
            loss = _as_numpy(pred)
            self._update(loss.sum(), loss.size)


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", **kwargs):
        super().__init__(name, **kwargs)
        self.metrics = [create(m) if isinstance(m, str) else m
                        for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric) if isinstance(metric, str)
                            else metric)

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def get(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get()
            names.append(n)
            values.append(v)
        return names, values


class CustomMetric(EvalMetric):
    def __init__(self, feval, name="custom", allow_extra_outputs=False,
                 **kwargs):
        super().__init__(f"custom({name})", **kwargs)
        self._feval = feval

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            v = self._feval(_as_numpy(label), _as_numpy(pred))
            if isinstance(v, tuple):
                s, n = v
                self._update(s, n)
            else:
                self._update(v)


def np(numpy_feval, name="custom", allow_extra_outputs=False):
    return CustomMetric(numpy_feval, name, allow_extra_outputs)


def create(metric, *args, **kwargs) -> EvalMetric:
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        return CompositeEvalMetric(metric)
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    return _registry.get(metric)(*args, **kwargs)
