"""``mx.onnx`` — ONNX export/import (parity: python/mxnet/onnx with
mx2onnx + onnx2mx, SURVEY.md §2.6 misc user surface).

- :func:`export_model` traces a Gluon block to jaxpr and emits standard
  ONNX (file-format compatible with stock onnx/onnxruntime; this image
  ships neither, so the wire layer is self-contained in proto.py).
- :func:`import_model` loads an ONNX file into a jit-executed callable.
"""
from .mx2onnx import export_model
from .onnx2mx import ONNXBlock, import_model

__all__ = ["export_model", "import_model", "ONNXBlock"]
