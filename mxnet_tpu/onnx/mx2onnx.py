"""mx2onnx: export a Gluon block to ONNX (parity: python/mxnet/onnx
mx2onnx, SURVEY.md §2.6 misc user surface).

TPU-native route: instead of walking an NNVM symbol graph, the model is
traced to a **jaxpr** (the same trace hybridize compiles) and each jax
primitive is emitted as standard ONNX ops — so any forward() code
exports, not just a fixed layer vocabulary.  Parameters become
initializers; the batch dimension is exported as written (ONNX reshapes
are shape-literal).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as onp

import jax
import jax.numpy as jnp

from .. import base as _base
from . import proto


class _Converter:
    def __init__(self):
        self.nodes: List = []
        self.initializers: List = []
        self.names: Dict[int, str] = {}   # id(jax var) -> onnx name
        self.counter = 0

    # ------------------------------------------------------------ helpers
    def fresh(self, hint="t"):
        self.counter += 1
        return f"{hint}_{self.counter}"

    def const(self, arr, hint="const"):
        name = self.fresh(hint)
        self.initializers.append(proto.tensor(name, onp.asarray(arr)))
        return name

    def name_of(self, v):
        """ONNX name for a jaxpr atom (var or literal)."""
        from jax._src.core import Literal
        if isinstance(v, Literal):
            val = onp.asarray(v.val)
            return self.const(val, "lit")
        if id(v) not in self.names:
            self.names[id(v)] = self.fresh("v")
        return self.names[id(v)]

    def emit(self, op, ins, n_out=1, **attrs):
        outs = [self.fresh(op.lower()) for _ in range(n_out)]
        self.nodes.append(proto.node(op, ins, outs, **attrs))
        return outs[0] if n_out == 1 else outs

    def bind(self, var, name):
        self.names[id(var)] = name

    # ------------------------------------------------------------ eqns
    def convert(self, jaxpr, consts):
        for cv, cval in zip(jaxpr.constvars, consts):
            self.bind(cv, self.const(onp.asarray(cval), "w"))
        for eq in jaxpr.eqns:
            self.eqn(eq)

    def eqn(self, eq):
        p = eq.primitive.name
        ins = [self.name_of(v) for v in eq.invars]
        params = eq.params

        def out(name):
            self.bind(eq.outvars[0], name)

        simple = {"add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div",
                  "max": "Max", "min": "Min", "exp": "Exp", "log": "Log",
                  "tanh": "Tanh", "logistic": "Sigmoid", "erf": "Erf",
                  "neg": "Neg", "abs": "Abs", "sqrt": "Sqrt",
                  "sign": "Sign", "floor": "Floor", "ceil": "Ceil",
                  "stop_gradient": "Identity", "copy": "Identity",
                  "gt": "Greater", "lt": "Less", "eq": "Equal",
                  "pow": "Pow", "and": "And", "or": "Or", "not": "Not"}
        simple["ge"] = "GreaterOrEqual"   # opset 12+: NaN-correct
        simple["le"] = "LessOrEqual"
        if p in simple:
            return out(self.emit(simple[p], ins))
        if p == "rsqrt":
            s = self.emit("Sqrt", ins)
            return out(self.emit("Reciprocal", [s]))
        if p == "integer_pow":
            y = params["y"]
            if y == 2:
                return out(self.emit("Mul", [ins[0], ins[0]]))
            e = self.const(onp.asarray(float(y), onp.float32))
            return out(self.emit("Pow", [ins[0], e]))
        if p == "select_n":
            # select_n(pred, x0, x1): pred True → x1
            return out(self.emit("Where", [ins[0], ins[2], ins[1]]))
        if p == "convert_element_type":
            to = proto.NP2ONNX[onp.dtype(params["new_dtype"])]
            return out(self.emit("Cast", ins, to=to))
        if p == "reshape":
            shp = self.const(onp.asarray(params["new_sizes"], onp.int64))
            return out(self.emit("Reshape", [ins[0], shp]))
        if p == "squeeze":
            axes = self.const(onp.asarray(params["dimensions"], onp.int64))
            return out(self.emit("Squeeze", [ins[0], axes]))
        if p == "expand_dims":
            axes = self.const(onp.asarray(params["dimensions"], onp.int64))
            return out(self.emit("Unsqueeze", [ins[0], axes]))
        if p == "transpose":
            return out(self.emit("Transpose", ins,
                                 perm=list(params["permutation"])))
        if p == "broadcast_in_dim":
            shape = list(params["shape"])
            bdims = list(params["broadcast_dimensions"])
            in_aval = eq.invars[0].aval
            # align rank: reshape so input dims land on broadcast_dimensions
            inter = [1] * len(shape)
            for src, dst in enumerate(bdims):
                inter[dst] = in_aval.shape[src]
            cur = ins[0]
            if list(in_aval.shape) != inter:
                shp = self.const(onp.asarray(inter, onp.int64))
                cur = self.emit("Reshape", [cur, shp])
            if inter != shape:
                tgt = self.const(onp.asarray(shape, onp.int64))
                cur = self.emit("Expand", [cur, tgt])
            return out(cur)
        if p == "concatenate":
            return out(self.emit("Concat", ins,
                                 axis=int(params["dimension"])))
        if p == "slice":
            starts = self.const(onp.asarray(params["start_indices"],
                                            onp.int64))
            ends = self.const(onp.asarray(params["limit_indices"],
                                          onp.int64))
            axes = self.const(onp.arange(len(params["start_indices"]),
                                         dtype=onp.int64))
            strides = params.get("strides") or \
                [1] * len(params["start_indices"])
            steps = self.const(onp.asarray(strides, onp.int64))
            return out(self.emit("Slice",
                                 [ins[0], starts, ends, axes, steps]))
        if p == "pad":
            cfg = params["padding_config"]
            if any(i != 0 for _, _, i in cfg):
                raise _base.MXNetError("interior pad not exportable")
            pads = [lo for lo, _, _ in cfg] + [hi for _, hi, _ in cfg]
            if min(pads) < 0:
                raise _base.MXNetError("negative pad not exportable")
            pv = self.const(onp.asarray(pads, onp.int64))
            return out(self.emit("Pad", [ins[0], pv, ins[1]]))
        if p in ("reduce_sum", "reduce_max", "reduce_min", "reduce_mean"):
            opn = {"reduce_sum": "ReduceSum", "reduce_max": "ReduceMax",
                   "reduce_min": "ReduceMin",
                   "reduce_mean": "ReduceMean"}[p]
            axes = self.const(onp.asarray(params["axes"], onp.int64))
            return out(self.emit(opn, [ins[0], axes], keepdims=0))
        if p == "argmax":
            return out(self.emit("ArgMax", ins,
                                 axis=int(params["axes"][0]), keepdims=0))
        if p == "reduce_window_max":
            return out(self._pool(eq, ins, "MaxPool"))
        if p == "reduce_window_sum":
            # Sum pool = AveragePool * window_size
            a = self._pool(eq, ins, "AveragePool")
            wd = params["window_dimensions"]
            k = float(onp.prod([d for d in wd if d > 1] or [1]))
            kc = self.const(onp.asarray(k, onp.float32))
            return out(self.emit("Mul", [a, kc]))
        if p == "conv_general_dilated":
            return out(self._conv(eq, ins))
        if p == "dot_general":
            return out(self._dot(eq, ins))
        if p in ("jit", "pjit", "closed_call", "core_call", "remat",
                 "checkpoint", "custom_jvp_call", "custom_vjp_call",
                 "custom_vjp_call_jaxpr"):
            return self._inline(eq, ins)
        raise _base.MXNetError(
            f"ONNX export: unsupported jax primitive {p!r}")

    # --------------------------------------------------------- compound
    def _pool(self, eq, ins, opn):
        params = eq.params
        wd = list(params["window_dimensions"])
        ws = list(params["window_strides"])
        pad = list(params["padding"])
        bd = params.get("base_dilation")
        wdl = params.get("window_dilation")
        if bd and any(d != 1 for d in bd):
            raise _base.MXNetError("pool base_dilation not exportable")
        if wdl and any(d != 1 for d in wdl):
            raise _base.MXNetError("pool window_dilation not exportable")
        # window must cover trailing spatial dims only (NCHW)
        if wd[0] != 1 or wd[1] != 1:
            raise _base.MXNetError(
                f"pool window over batch/channel dims not exportable {wd}")
        kernel = wd[2:]
        strides = ws[2:]
        pads = [lo for lo, _ in pad[2:]] + [hi for _, hi in pad[2:]]
        kw = dict(kernel_shape=kernel, strides=strides, pads=pads)
        if opn == "AveragePool":
            kw["count_include_pad"] = 1
        return self.emit(opn, [ins[0]], **kw)

    def _conv(self, eq, ins):
        params = eq.params
        dn = params["dimension_numbers"]
        lhs_spec, rhs_spec, out_spec = dn
        nd = len(lhs_spec) - 2
        want_lhs = tuple([0, 1] + list(range(2, nd + 2)))
        if (tuple(lhs_spec) != want_lhs or tuple(rhs_spec) != want_lhs or
                tuple(out_spec) != want_lhs):
            raise _base.MXNetError(
                f"conv dimension_numbers {dn} not NCHW/OIHW")
        if any(d != 1 for d in params["lhs_dilation"]):
            raise _base.MXNetError("transposed conv not exportable yet")
        pads = [lo for lo, _ in params["padding"]] + \
            [hi for _, hi in params["padding"]]
        return self.emit(
            "Conv", ins, kernel_shape=list(eq.invars[1].aval.shape[2:]),
            strides=list(params["window_strides"]),
            dilations=list(params["rhs_dilation"]), pads=pads,
            group=int(params["feature_group_count"]))

    def _dot(self, eq, ins):
        (lc, rc), (lb, rb) = eq.params["dimension_numbers"]
        ln = len(eq.invars[0].aval.shape)
        rn = len(eq.invars[1].aval.shape)
        letters = "abcdefghijklmnopqrstuvwxyz"
        names = {}
        idx = 0

        def letter(side, d):
            nonlocal idx
            if (side, d) not in names:
                names[(side, d)] = letters[idx]
                idx += 1
            return names[(side, d)]

        for bl, br in zip(lb, rb):
            names[("r", br)] = letter("l", bl)
        for cl, cr in zip(lc, rc):
            names[("r", cr)] = letter("l", cl)
        lhs = "".join(letter("l", d) for d in range(ln))
        rhs = "".join(letter("r", d) for d in range(rn))
        out_l = [letter("l", d) for d in range(ln)
                 if d not in lc and d not in lb]
        out_r = [letter("r", d) for d in range(rn)
                 if d not in rc and d not in rb]
        batch = [letter("l", d) for d in lb]
        eqn = f"{lhs},{rhs}->{''.join(batch + out_l + out_r)}"
        return self.emit("Einsum", ins, equation=eqn)

    def _inline(self, eq, ins):
        params = eq.params
        sub = params.get("jaxpr") or params.get("call_jaxpr") or \
            params.get("fun_jaxpr")
        if sub is None:
            raise _base.MXNetError(
                f"cannot inline call primitive {eq.primitive.name}")
        consts = ()
        inner = sub
        if hasattr(sub, "jaxpr"):       # ClosedJaxpr
            consts = sub.consts
            inner = sub.jaxpr
        for cv, cval in zip(inner.constvars, consts):
            self.bind(cv, self.const(onp.asarray(cval), "w"))
        n_in = len(inner.invars)
        for v, nm in zip(inner.invars, ins[len(ins) - n_in:]):
            self.bind(v, nm)
        for e in inner.eqns:
            self.eqn(e)
        for ov, outer in zip(inner.outvars, eq.outvars):
            self.bind(outer, self.name_of(ov))


def export_model(net, path, input_shapes, input_dtype="float32",
                 opset=13):
    """Export an initialized Gluon block to ``path`` (ONNX file).

    input_shapes: one shape tuple (single input) or a list of them.
    Returns the path.  Inference semantics (training_mode False: BN uses
    running stats, dropout is identity) — matching upstream
    mx2onnx.export_model's export of inference graphs.
    """
    from ..ndarray import NDArray
    from ..ndarray.ndarray import swap_values

    if opset < 13:
        raise _base.MXNetError(
            "export emits opset-13 node forms (Squeeze/ReduceSum axes as "
            f"inputs, GreaterOrEqual, ...); opset={opset} < 13 would "
            "declare a version the nodes violate")
    if isinstance(input_shapes, tuple):
        input_shapes = [input_shapes]
    dt = onp.dtype(input_dtype)
    xs = [jnp.asarray(onp.zeros(s, dt)) for s in input_shapes]

    # settle deferred shapes
    with _base.training_mode(False):
        rec = _base.set_recording(False)
        try:
            net(*[NDArray(x) for x in xs])
        finally:
            _base.set_recording(rec)

    items, seen = [], set()
    for name, prm in net.collect_params().items():
        if id(prm) in seen or prm._data is None:
            continue
        seen.add(id(prm))
        items.append((name, prm))
    pvals = tuple(prm._data.jax for _, prm in items)

    def fwd(param_vals, *data):
        with swap_values([prm._data for _, prm in items], param_vals):
            with _base.training_mode(False):
                rec = _base.set_recording(False)
                try:
                    outn = net.forward(*[NDArray(d) for d in data])
                finally:
                    _base.set_recording(rec)
            outs = outn if isinstance(outn, (tuple, list)) else [outn]
            return tuple(o.jax for o in outs)

    closed = jax.make_jaxpr(fwd)(pvals, *xs)
    cv = _Converter()
    # bind params as named initializers, data as graph inputs
    jaxpr = closed.jaxpr
    flat_in = jaxpr.invars
    n_params = len(pvals)
    graph_inputs = []
    for i, (name, prm) in enumerate(items):
        nm = name.replace(".", "_")
        cv.initializers.append(
            proto.tensor(nm, onp.asarray(prm._data.jax)))
        cv.bind(flat_in[i], nm)
    for j, x in enumerate(xs):
        nm = "data" if len(xs) == 1 else f"data{j}"
        cv.bind(flat_in[n_params + j], nm)
        graph_inputs.append(proto.value_info(nm, dt, x.shape))
    cv.convert(jaxpr, closed.consts)

    outputs = []
    for k, ov in enumerate(jaxpr.outvars):
        nm = cv.name_of(ov)
        outputs.append(proto.value_info(
            nm, onp.dtype(ov.aval.dtype), ov.aval.shape))
    g = proto.graph(cv.nodes, "mxnet_tpu_export", cv.initializers,
                    graph_inputs, outputs)
    data = proto.model(g, opset=opset)
    with open(path, "wb") as f:
        f.write(data)
    return path
