"""onnx2mx: import an ONNX model and run it with jax/XLA (parity:
python/mxnet/onnx onnx2mx import_model, SURVEY.md §2.6).

The imported graph executes as jnp ops (so it runs on TPU like any other
block) over the op vocabulary mx2onnx emits plus common basics — also the
in-repo verification path for exports, since the image ships no
onnxruntime.
"""
from __future__ import annotations

from typing import Dict, List

import numpy as onp

import jax
import jax.numpy as jnp
from jax import lax

from .. import base as _base
from ..ndarray import NDArray
from . import proto


def _pool_args(attrs, nd_spatial):
    k = attrs["kernel_shape"]
    s = attrs.get("strides") or [1] * len(k)
    pads = attrs.get("pads") or [0] * 2 * len(k)
    n = len(k)
    pairs = [(pads[i], pads[n + i]) for i in range(n)]
    return k, s, pairs


class _Evaluator:
    def __init__(self, model):
        self.graph = model["graph"]
        self.opset = model["opset"]

    def run(self, feeds: Dict[str, jnp.ndarray]):
        # initializers stay CONCRETE numpy: under jit, jnp.asarray of an
        # int64 array stages the int64→int32 conversion and yields a
        # tracer, which shape-consuming ops (Reshape/Slice) must not see;
        # numeric ops coerce numpy operands transparently
        env: Dict[str, jnp.ndarray] = dict(self.graph["initializers"])
        env.update({k: jnp.asarray(v) for k, v in feeds.items()})
        for nd in self.graph["nodes"]:
            outs = self.op(nd, [env[i] for i in nd["inputs"] if i])
            if not isinstance(outs, (tuple, list)):
                outs = [outs]
            for name, val in zip(nd["outputs"], outs):
                env[name] = val
        return [env[name] for name, _, _ in self.graph["outputs"]]

    def op(self, nd, x):
        op = nd["op"]
        a = nd["attrs"]
        ew = {"Add": jnp.add, "Sub": jnp.subtract, "Mul": jnp.multiply,
              "Div": jnp.divide, "Max": jnp.maximum, "Min": jnp.minimum,
              "Pow": jnp.power, "Exp": jnp.exp, "Log": jnp.log,
              "Tanh": jnp.tanh, "Sqrt": jnp.sqrt, "Neg": jnp.negative,
              "Abs": jnp.abs, "Sign": jnp.sign, "Floor": jnp.floor,
              "Ceil": jnp.ceil, "Reciprocal": lambda v: 1.0 / v,
              "Sigmoid": jax.nn.sigmoid, "Erf": jax.scipy.special.erf,
              "Relu": jax.nn.relu, "Identity": lambda v: v,
              "Greater": jnp.greater, "Less": jnp.less,
              "GreaterOrEqual": jnp.greater_equal,
              "LessOrEqual": jnp.less_equal,
              "Equal": jnp.equal, "Not": jnp.logical_not,
              "And": jnp.logical_and, "Or": jnp.logical_or}
        if op in ew:
            return ew[op](*x)
        if op == "Where":
            return jnp.where(x[0], x[1], x[2])
        if op == "Cast":
            return x[0].astype(proto.ONNX2NP[int(a["to"])])
        if op == "Reshape":
            return jnp.reshape(x[0], [int(v) for v in onp.asarray(x[1])])
        if op == "Squeeze":
            axes = tuple(int(v) for v in onp.asarray(x[1])) if len(x) > 1 \
                else tuple(a.get("axes", []))
            return jnp.squeeze(x[0], axis=axes or None)
        if op == "Unsqueeze":
            axes = tuple(int(v) for v in onp.asarray(x[1])) if len(x) > 1 \
                else tuple(a.get("axes", []))
            return jnp.expand_dims(x[0], axis=axes)
        if op == "Transpose":
            return jnp.transpose(x[0], a.get("perm"))
        if op == "Expand":
            shape = [int(v) for v in onp.asarray(x[1])]
            return jnp.broadcast_to(
                x[0], onp.broadcast_shapes(tuple(x[0].shape),
                                           tuple(shape)))
        if op == "Concat":
            return jnp.concatenate(x, axis=int(a["axis"]))
        if op == "Slice":
            starts = onp.asarray(x[1]).tolist()
            ends = onp.asarray(x[2]).tolist()
            axes = onp.asarray(x[3]).tolist() if len(x) > 3 else \
                list(range(len(starts)))
            steps = onp.asarray(x[4]).tolist() if len(x) > 4 else \
                [1] * len(starts)
            sl = [slice(None)] * x[0].ndim
            for st, en, ax, sp in zip(starts, ends, axes, steps):
                sl[ax] = slice(st, en, sp)
            return x[0][tuple(sl)]
        if op == "Pad":
            pads = onp.asarray(x[1]).tolist()
            n = x[0].ndim
            cfg = [(pads[i], pads[n + i]) for i in range(n)]
            cval = onp.asarray(x[2]).item() if len(x) > 2 else 0.0
            return jnp.pad(x[0], cfg, constant_values=cval)
        if op in ("ReduceSum", "ReduceMax", "ReduceMin", "ReduceMean"):
            axes = tuple(int(v) for v in onp.asarray(x[1])) if len(x) > 1 \
                else tuple(a.get("axes", []))
            keep = bool(a.get("keepdims", 1))
            fn = {"ReduceSum": jnp.sum, "ReduceMax": jnp.max,
                  "ReduceMin": jnp.min, "ReduceMean": jnp.mean}[op]
            return fn(x[0], axis=axes or None, keepdims=keep)
        if op == "ArgMax":
            ax = int(a.get("axis", 0))
            r = jnp.argmax(x[0], axis=ax)
            if a.get("keepdims", 1):       # ONNX default keepdims=1
                r = jnp.expand_dims(r, ax)
            return r
        if op == "Flatten":
            ax = int(a.get("axis", 1))
            return jnp.reshape(x[0], (int(onp.prod(x[0].shape[:ax])), -1))
        if op == "MatMul":
            return jnp.matmul(x[0], x[1])
        if op == "Gemm":
            y = jnp.matmul(
                x[0].T if a.get("transA") else x[0],
                x[1].T if a.get("transB") else x[1])
            y = y * a.get("alpha", 1.0)
            if len(x) > 2:
                y = y + x[2] * a.get("beta", 1.0)
            return y
        if op == "Einsum":
            return jnp.einsum(a["equation"], *x)
        if op == "Conv":
            k, s, pairs = _pool_args(
                {"kernel_shape": a.get("kernel_shape",
                                       list(x[1].shape[2:])),
                 "strides": a.get("strides"), "pads": a.get("pads")},
                x[0].ndim - 2)
            y = lax.conv_general_dilated(
                x[0], x[1], window_strides=s, padding=pairs,
                rhs_dilation=a.get("dilations"),
                feature_group_count=int(a.get("group", 1)))
            if len(x) > 2:
                bshape = (1, -1) + (1,) * (x[0].ndim - 2)
                y = y + x[2].reshape(bshape)
            return y
        if op in ("MaxPool", "AveragePool"):
            k, s, pairs = _pool_args(a, x[0].ndim - 2)
            full_k = (1, 1) + tuple(k)
            full_s = (1, 1) + tuple(s)
            full_p = [(0, 0), (0, 0)] + pairs
            if op == "MaxPool":
                init = -jnp.inf if jnp.issubdtype(
                    x[0].dtype, jnp.floating) else \
                    jnp.iinfo(x[0].dtype).min
                return lax.reduce_window(x[0], init, lax.max, full_k,
                                         full_s, full_p)
            ssum = lax.reduce_window(x[0], 0.0, lax.add, full_k, full_s,
                                     full_p)
            if a.get("count_include_pad"):
                return ssum / float(onp.prod(k))
            ones = jnp.ones_like(x[0])
            cnt = lax.reduce_window(ones, 0.0, lax.add, full_k, full_s,
                                    full_p)
            return ssum / cnt
        if op == "GlobalAveragePool":
            return jnp.mean(x[0], axis=tuple(range(2, x[0].ndim)),
                            keepdims=True)
        if op == "BatchNormalization":
            xv, scale, b, mean, var = x[:5]
            eps = a.get("epsilon", 1e-5)
            shape = (1, -1) + (1,) * (xv.ndim - 2)
            return (xv - mean.reshape(shape)) / jnp.sqrt(
                var.reshape(shape) + eps) * scale.reshape(shape) + \
                b.reshape(shape)
        if op == "Softmax":
            return jax.nn.softmax(x[0], axis=int(a.get("axis", -1)))
        if op == "Constant":
            return jnp.asarray(a["value"])
        if op == "Dropout":
            return x[0]
        raise _base.MXNetError(f"ONNX import: unsupported op {op!r}")


class ONNXBlock:
    """Callable imported model: NDArray(s) in → NDArray(s) out, jitted."""

    def __init__(self, model):
        self._ev = _Evaluator(model)
        self.input_names = [n for n, _, _ in
                            self._ev.graph["inputs"]]
        self._jitted = jax.jit(
            lambda feeds: self._ev.run(feeds))

    def __call__(self, *args):
        feeds = {}
        for name, arg in zip(self.input_names, args):
            feeds[name] = arg.jax if isinstance(arg, NDArray) else \
                jnp.asarray(arg)
        outs = self._jitted(feeds)
        res = [NDArray(o) for o in outs]
        return res[0] if len(res) == 1 else res


def import_model(path):
    """Load an ONNX file → (ONNXBlock, arg_params, aux_params) — the
    callable plus the initializer dict, mirroring upstream
    onnx2mx.import_model's (sym, arg_params, aux_params) contract."""
    with open(path, "rb") as f:
        model = proto.parse_model(f.read())
    blk = ONNXBlock(model)
    args = {k: NDArray(jnp.asarray(v))
            for k, v in model["graph"]["initializers"].items()}
    return blk, args, {}
