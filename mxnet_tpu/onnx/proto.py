"""Minimal ONNX protobuf wire codec.

The environment ships no ``onnx`` package, so the subset of the public
``onnx.proto`` schema that the exporter emits is encoded/decoded directly
at the protobuf wire level (field numbers follow the public ONNX schema;
files are standard ONNX and load in stock onnx/onnxruntime).

Parity role: the serialization layer under python/mxnet/onnx (mx2onnx /
onnx2mx), SURVEY.md §2.6 misc user surface.
"""
from __future__ import annotations

import struct
from typing import Dict, List, Tuple

import numpy as onp

# ---- ONNX enums (public schema values) ----
# TensorProto.DataType
FLOAT, UINT8, INT8, INT32, INT64, BOOL, FLOAT16, DOUBLE = \
    1, 2, 3, 6, 7, 9, 10, 11
BFLOAT16 = 16

NP2ONNX = {
    onp.dtype(onp.float32): FLOAT, onp.dtype(onp.uint8): UINT8,
    onp.dtype(onp.int8): INT8, onp.dtype(onp.int32): INT32,
    onp.dtype(onp.int64): INT64, onp.dtype(onp.bool_): BOOL,
    onp.dtype(onp.float16): FLOAT16, onp.dtype(onp.float64): DOUBLE,
}
try:  # bf16 (the AMP default target) rides ml_dtypes
    import ml_dtypes as _mld
    NP2ONNX[onp.dtype(_mld.bfloat16)] = BFLOAT16
except ImportError:
    pass
ONNX2NP = {v: k for k, v in NP2ONNX.items()}

# AttributeProto.AttributeType
A_FLOAT, A_INT, A_STRING, A_TENSOR, A_FLOATS, A_INTS, A_STRINGS = \
    1, 2, 3, 4, 6, 7, 8


# ------------------------------------------------------------ wire writer

def _varint(n: int) -> bytes:
    if n < 0:
        n += 1 << 64
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _key(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


class W:
    """Append-only message writer."""

    def __init__(self):
        self.buf = bytearray()

    def int_(self, field, v):
        self.buf += _key(field, 0) + _varint(int(v))
        return self

    def bytes_(self, field, b):
        self.buf += _key(field, 2) + _varint(len(b)) + bytes(b)
        return self

    def str_(self, field, s):
        return self.bytes_(field, s.encode())

    def msg(self, field, w: "W"):
        return self.bytes_(field, w.buf)

    def float_(self, field, v):
        self.buf += _key(field, 5) + struct.pack("<f", float(v))
        return self

    def packed_int64(self, field, vals):
        body = b"".join(_varint(int(v)) for v in vals)
        return self.bytes_(field, body)

    def packed_float(self, field, vals):
        return self.bytes_(field, struct.pack(f"<{len(vals)}f", *vals))

    def done(self) -> bytes:
        return bytes(self.buf)


# ------------------------------------------------------------ wire reader

def _read_varint(buf, p):
    n = shift = 0
    while True:
        b = buf[p]
        p += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, p
        shift += 7


def parse(buf) -> Dict[int, List]:
    """Decode one message level → {field: [value, ...]} (wire-typed:
    ints for varint/fixed, bytes for length-delimited)."""
    out: Dict[int, List] = {}
    p = 0
    n = len(buf)
    while p < n:
        key, p = _read_varint(buf, p)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, p = _read_varint(buf, p)
        elif wire == 2:
            ln, p = _read_varint(buf, p)
            v = bytes(buf[p:p + ln])
            p += ln
        elif wire == 5:
            v = struct.unpack("<I", buf[p:p + 4])[0]
            p += 4
        elif wire == 1:
            v = struct.unpack("<Q", buf[p:p + 8])[0]
            p += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        out.setdefault(field, []).append(v)
    return out


def parse_packed_int64(b: bytes) -> List[int]:
    vals, p = [], 0
    while p < len(b):
        v, p = _read_varint(b, p)
        if v >= 1 << 63:
            v -= 1 << 64
        vals.append(v)
    return vals


# ------------------------------------------------------- ONNX constructors

def tensor(name: str, arr: onp.ndarray) -> W:
    """TensorProto: dims=1, data_type=2, name=8, raw_data=9."""
    arr = onp.ascontiguousarray(arr)
    w = W()
    for d in arr.shape:
        w.int_(1, d)
    w.int_(2, NP2ONNX[arr.dtype])
    w.str_(8, name)
    w.bytes_(9, arr.tobytes())
    return w


def parse_tensor(b: bytes) -> Tuple[str, onp.ndarray]:
    f = parse(b)
    dims = [int(v) for v in f.get(1, [])]
    dtype = ONNX2NP[int(f[2][0])]
    name = f.get(8, [b""])[0].decode()
    if 9 in f:
        arr = onp.frombuffer(f[9][0], dtype=dtype).reshape(dims)
    elif 4 in f:   # float_data (packed)
        arr = onp.array(
            struct.unpack(f"<{len(f[4][0]) // 4}f", f[4][0]),
            dtype=onp.float32).reshape(dims)
    elif 7 in f:   # int64_data (packed)
        arr = onp.array(parse_packed_int64(f[7][0]),
                        dtype=onp.int64).reshape(dims)
    else:
        arr = onp.zeros(dims, dtype)
    return name, arr


def attr(name: str, value) -> W:
    """AttributeProto: name=1 f=2 i=3 s=4 t=5 floats=7 ints=8 type=20."""
    w = W()
    w.str_(1, name)
    if isinstance(value, bool):
        w.int_(3, int(value)).int_(20, A_INT)
    elif isinstance(value, int):
        w.int_(3, value).int_(20, A_INT)
    elif isinstance(value, float):
        w.float_(2, value).int_(20, A_FLOAT)
    elif isinstance(value, str):
        w.str_(4, value).int_(20, A_STRING)
    elif isinstance(value, onp.ndarray):
        w.msg(5, tensor("", value)).int_(20, A_TENSOR)
    elif isinstance(value, (list, tuple)):
        if value and isinstance(value[0], float):
            w.packed_float(7, value).int_(20, A_FLOATS)
        else:
            w.packed_int64(8, value).int_(20, A_INTS)
    else:
        raise TypeError(f"attr {name}: {type(value)}")
    return w


def parse_attr(b: bytes):
    f = parse(b)
    name = f[1][0].decode()
    typ = int(f.get(20, [0])[0])
    if typ == A_INT:
        return name, int(f[3][0]) - (1 << 64 if f[3][0] >= 1 << 63 else 0)
    if typ == A_FLOAT:
        return name, struct.unpack("<f", struct.pack("<I", f[2][0]))[0]
    if typ == A_STRING:
        return name, f[4][0].decode()
    if typ == A_TENSOR:
        return name, parse_tensor(f[5][0])[1]
    if typ == A_INTS:
        return name, parse_packed_int64(f[8][0]) if 8 in f else []
    if typ == A_FLOATS:
        raw = f.get(7, [b""])[0]
        return name, list(struct.unpack(f"<{len(raw) // 4}f", raw))
    raise ValueError(f"attr {name}: unsupported type {typ}")


def node(op_type: str, inputs, outputs, name="", **attrs) -> W:
    """NodeProto: input=1 output=2 name=3 op_type=4 attribute=5."""
    w = W()
    for i in inputs:
        w.str_(1, i)
    for o in outputs:
        w.str_(2, o)
    if name:
        w.str_(3, name)
    w.str_(4, op_type)
    for k, v in attrs.items():
        w.msg(5, attr(k, v))
    return w


def value_info(name: str, dtype, shape) -> W:
    """ValueInfoProto{name=1, type=2}; TypeProto{tensor_type=1};
    Tensor{elem_type=1, shape=2}; TensorShapeProto{dim=1};
    Dimension{dim_value=1, dim_param=2}."""
    shp = W()
    for d in shape:
        dim = W()
        if isinstance(d, str):
            dim.str_(2, d)
        else:
            dim.int_(1, int(d))
        shp.msg(1, dim)
    tt = W()
    tt.int_(1, NP2ONNX[onp.dtype(dtype)])
    tt.msg(2, shp)
    tp = W()
    tp.msg(1, tt)
    w = W()
    w.str_(1, name)
    w.msg(2, tp)
    return w


def parse_value_info(b: bytes):
    f = parse(b)
    name = f[1][0].decode()
    tt = parse(parse(f[2][0])[1][0])
    elem = int(tt[1][0])
    dims = []
    if 2 in tt:
        for d in parse(tt[2][0]).get(1, []):
            df = parse(d)
            dims.append(int(df[1][0]) if 1 in df
                        else df.get(2, [b"?"])[0].decode())
    return name, ONNX2NP.get(elem, onp.dtype(onp.float32)), dims


def graph(nodes, name, initializers, inputs, outputs) -> W:
    """GraphProto: node=1 name=2 initializer=5 input=11 output=12."""
    w = W()
    for nd in nodes:
        w.msg(1, nd)
    w.str_(2, name)
    for t in initializers:
        w.msg(5, t)
    for vi in inputs:
        w.msg(11, vi)
    for vi in outputs:
        w.msg(12, vi)
    return w


def model(graph_w: W, opset: int = 13, producer="mxnet_tpu") -> bytes:
    """ModelProto: ir_version=1 producer_name=2 graph=7 opset_import=8."""
    ops = W()
    ops.str_(1, "")          # default domain
    ops.int_(2, opset)
    w = W()
    w.int_(1, 8)             # IR version 8
    w.str_(2, producer)
    w.msg(7, graph_w)
    w.msg(8, ops)
    return w.done()


def parse_model(buf: bytes):
    """→ dict(graph=..., opset=int).  graph: dict(nodes, initializers,
    inputs, outputs, name)."""
    f = parse(buf)
    g = parse(f[7][0])
    nodes = []
    for nb in g.get(1, []):
        nf = parse(nb)
        nodes.append({
            "op": nf[4][0].decode(),
            "inputs": [x.decode() for x in nf.get(1, [])],
            "outputs": [x.decode() for x in nf.get(2, [])],
            "name": nf.get(3, [b""])[0].decode(),
            "attrs": dict(parse_attr(a) for a in nf.get(5, [])),
        })
    inits = dict(parse_tensor(t) for t in g.get(5, []))
    ins = [parse_value_info(v) for v in g.get(11, [])]
    outs = [parse_value_info(v) for v in g.get(12, [])]
    opset = 13
    for o in f.get(8, []):
        of = parse(o)
        if of.get(1, [b""])[0] == b"":
            opset = int(of.get(2, [13])[0])
    return {"graph": {"nodes": nodes, "initializers": inits,
                      "inputs": ins, "outputs": outs,
                      "name": g.get(2, [b""])[0].decode()},
            "opset": opset}
