"""Foundational utilities: dtype handling, registries, global modes.

Role parity: the dtype/registry plumbing that upstream MXNet implements in
``python/mxnet/base.py`` + ``dmlc::Parameter`` (see SURVEY.md §5.6).  Here the
"C ABI" disappears: ops are pure JAX functions registered in Python, and the
parameter-struct metadata lives on the registered op wrapper itself.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, Dict, Optional

import jax.numpy as jnp
import numpy as onp

__all__ = [
    "MXNetError",
    "numeric_types",
    "integer_types",
    "string_types",
    "dtype_np_to_jax",
    "canonical_dtype",
    "registry",
]


class MXNetError(RuntimeError):
    """Framework-level error (parity with mxnet.base.MXNetError)."""


numeric_types = (float, int, onp.generic, onp.ndarray)
integer_types = (int, onp.integer)
string_types = (str,)

# dtype canonicalization -----------------------------------------------------

_DTYPE_ALIASES: Dict[str, Any] = {
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float64": jnp.float64,
    "uint8": jnp.uint8,
    "uint16": jnp.uint16,
    "uint32": jnp.uint32,
    "int8": jnp.int8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "bool": jnp.bool_,
}


def canonical_dtype(dtype) -> onp.dtype:
    """Return a numpy dtype object for any accepted dtype spec."""
    if dtype is None:
        return onp.dtype("float32")
    if isinstance(dtype, str):
        if dtype in _DTYPE_ALIASES:
            return onp.dtype(_DTYPE_ALIASES[dtype])
        return onp.dtype(dtype)
    return onp.dtype(dtype)


def dtype_np_to_jax(dtype):
    return jnp.dtype(canonical_dtype(dtype))


# Simple name->object registry (parity: dmlc registry used for optimizers,
# initializers, metrics, kvstore types).


class _Registry:
    def __init__(self, kind: str):
        self.kind = kind
        self._entries: Dict[str, Any] = {}

    def register(self, name: Optional[str] = None, obj: Any = None):
        def _do(o, nm):
            key = (nm or getattr(o, "__name__", None) or str(o)).lower()
            self._entries[key] = o
            return o

        if obj is not None:
            return _do(obj, name)

        def deco(o):
            return _do(o, name)

        return deco

    def get(self, name: str):
        key = name.lower()
        if key not in self._entries:
            raise MXNetError(
                f"Unknown {self.kind} '{name}'. Registered: {sorted(self._entries)}"
            )
        return self._entries[key]

    def find(self, name: str):
        return self._entries.get(name.lower())

    def names(self):
        return sorted(self._entries)


_REGISTRIES: Dict[str, _Registry] = {}


def registry(kind: str) -> _Registry:
    if kind not in _REGISTRIES:
        _REGISTRIES[kind] = _Registry(kind)
    return _REGISTRIES[kind]


# Global training/inference mode (parity: autograd train_mode/predict_mode).

_STATE = threading.local()


def is_training() -> bool:
    return getattr(_STATE, "train_mode", False)


def set_training(flag: bool) -> bool:
    prev = is_training()
    _STATE.train_mode = bool(flag)
    return prev


@contextlib.contextmanager
def training_mode(flag: bool):
    prev = set_training(flag)
    try:
        yield
    finally:
        set_training(prev)


def is_recording() -> bool:
    return getattr(_STATE, "recording", False)


# Execution-platform hint: ops.invoke runs pure jax functions under
# jax.vjp, where inputs are tracers that no longer carry a device, yet
# device-dependent dispatch decisions (Pallas compiled vs interpret) must
# follow the NDArray's context, not the process default backend — on a
# TPU host a cpu()-context op still executes on the CPU XLA backend.

def exec_platform() -> Optional[str]:
    return getattr(_STATE, "exec_platform", None)


@contextlib.contextmanager
def executing_on(platform: Optional[str]):
    prev = exec_platform()
    _STATE.exec_platform = platform
    try:
        yield
    finally:
        _STATE.exec_platform = prev


def resolve_exec_platform(x=None) -> str:
    """Platform a jax computation over ``x`` will actually execute on.

    A concrete array knows its device; under a trace (jax.vjp in
    ops.invoke, jit) fall back to the dispatcher's execution-platform
    hint, then to the process default backend.  Deciding from the global
    default alone is wrong on a TPU host running a cpu()-context op — the
    exact case the cross-backend consistency battery exercises.
    """
    import jax
    if x is not None and isinstance(x, jax.Array) \
            and not isinstance(x, jax.core.Tracer):
        try:
            return next(iter(x.devices())).platform
        except Exception:
            pass
    hint = exec_platform()
    return hint if hint is not None else jax.default_backend()


def set_recording(flag: bool) -> bool:
    prev = is_recording()
    _STATE.recording = bool(flag)
    return prev


# Ambient auxiliary-loss collector (MoE router losses etc.): layers append
# during forward, loss functions drain within the same trace/tape.  Traced
# (jit) values may only be recorded inside an aux_collection scope — the
# scope owner guarantees the loss is computed within the SAME trace, so
# tracers never leak (e.g. out of a CachedOp forward into an eager loss).

def aux_collection_active() -> bool:
    return getattr(_STATE, "aux_collect", False)


def set_aux_collection(flag: bool) -> bool:
    prev = aux_collection_active()
    _STATE.aux_collect = bool(flag)
    return prev


def record_aux_loss(x) -> None:
    if not hasattr(_STATE, "aux_losses"):
        _STATE.aux_losses = []
    _STATE.aux_losses.append(x)


def pop_aux_losses() -> list:
    out = list(getattr(_STATE, "aux_losses", ()))
    _STATE.aux_losses = []
    return out


# Numeric promotion helper shared by the nd namespace.

def wrap_scalar(x, like_dtype=None):
    if isinstance(x, (int, float, bool)):
        return jnp.asarray(x, dtype=like_dtype)
    return x
