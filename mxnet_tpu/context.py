"""Device / context model.

Parity target: ``python/mxnet/context.py`` (1.x) / ``device.py`` (2.x) —
``mx.cpu()``, ``mx.gpu(i)``, default-context scoping, ``num_gpus()``.

TPU-first design: a :class:`Context` is a thin named handle resolving to a
``jax.Device``.  ``gpu(i)`` is kept as a compatibility alias that resolves to
the i-th accelerator so existing scripts run unmodified; ``tpu(i)`` is the
native spelling.  There are no per-device streams to manage — XLA's async
dispatch replaces MXNet's stream/engine machinery (SURVEY.md §7.1).
"""
from __future__ import annotations

import threading
from typing import List, Optional

import jax

__all__ = ["Context", "Device", "cpu", "gpu", "tpu", "cpu_pinned",
           "num_gpus", "num_tpus", "current_context", "current_device"]

_state = threading.local()


class Context:
    """A device handle: ``Context('tpu', 0)``.

    Acts as a context manager setting the default context, mirroring
    ``with mx.gpu(0): ...`` semantics.
    """

    devtype2id = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "cpu_shared": 5, "tpu": 6}
    devid2type = {v: k for k, v in devtype2id.items()}

    def __init__(self, device_type: str = "cpu", device_id: int = 0):
        if device_type not in self.devtype2id:
            raise ValueError(f"unknown device type {device_type!r}")
        self.device_type = device_type
        self.device_id = int(device_id)

    # -- identity ----------------------------------------------------------
    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    # -- resolution --------------------------------------------------------
    @property
    def jax_device(self) -> jax.Device:
        """Resolve to a concrete jax.Device.

        cpu→host backend; gpu/tpu→the default accelerator backend.  ``gpu`` is
        an alias kept so GluonCV-era scripts keep working on TPU.
        """
        if self.device_type in ("cpu", "cpu_pinned", "cpu_shared"):
            devs = _backend_devices("cpu")
        else:
            devs = accelerator_devices()
            if not devs:
                devs = _backend_devices("cpu")
        return devs[self.device_id % len(devs)]

    # convenience parity helpers
    def empty_cache(self):  # MXNet: ctx.empty_cache() — XLA manages HBM pools
        return None


Device = Context  # 2.x name


_DEVICE_CACHE: dict = {}


def _backend_devices(platform: str) -> List[jax.Device]:
    """PROCESS-LOCAL devices of a platform: MXNet context semantics are
    per-worker (each worker's cpu(0)/tpu(0) is its own), and in a
    multi-process job placing eager arrays on another process's device is
    both wrong and unsupported.  Successful lookups are cached — device
    enumeration sits on the eager dispatch hot path — but FAILURES are
    not: a TPU plugin that initializes late relative to the first
    tpu-context lookup must become visible on retry, not stay pinned to
    the [] result for the life of the process.  utils.platform.force_cpu()
    invalidates when it swaps the backend out."""
    devs = _DEVICE_CACHE.get(platform)
    if devs is None:
        try:
            devs = list(jax.local_devices(backend=platform))
        except RuntimeError:
            return []
        if devs:
            _DEVICE_CACHE[platform] = devs
    return devs


# lru_cache-compatible invalidation shim: force_cpu() and older callers
# invalidate via _backend_devices.cache_clear()
_backend_devices.cache_clear = _DEVICE_CACHE.clear  # type: ignore[attr-defined]


_ACCEL_CACHE: Optional[List[jax.Device]] = None


def accelerator_devices() -> List[jax.Device]:
    """All non-host devices (TPU chips), else empty.

    The result — INCLUDING an empty one — is cached: this sits on the
    eager dispatch hot path (``current_context`` consults it per op on
    an empty context stack), so a CPU-only host must not re-enumerate
    devices forever.  The late-TPU-plugin case is handled by
    invalidation instead: ``utils.platform`` clears the cache from
    ``force_cpu()`` and whenever ``probe_accelerator``/``init_backend``
    observe the backend coming up."""
    global _ACCEL_CACHE
    if _ACCEL_CACHE is None:
        _ACCEL_CACHE = [d for d in jax.local_devices()
                        if d.platform != "cpu"]
    return _ACCEL_CACHE


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    return Context("cpu_pinned", device_id)


def gpu(device_id: int = 0) -> Context:
    return Context("gpu", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def num_gpus() -> int:
    """Compat: reports accelerator count so ``ctx = mx.gpu() if mx.context.
    num_gpus() else mx.cpu()`` idioms pick the TPU."""
    return len(accelerator_devices())


def num_tpus() -> int:
    return len(accelerator_devices())


def current_context() -> Context:
    stack = getattr(_state, "stack", None)
    if stack:
        return stack[-1]
    return Context("tpu", 0) if accelerator_devices() else Context("cpu", 0)


current_device = current_context


def _push_context(ctx: Context):
    if not hasattr(_state, "stack"):
        _state.stack = []
    _state.stack.append(ctx)


def _pop_context():
    _state.stack.pop()


class _CtxScope:
    def __init__(self, ctx):
        self.ctx = ctx

    def __enter__(self):
        _push_context(self.ctx)
        return self.ctx

    def __exit__(self, *a):
        _pop_context()


# Attach context-manager behavior to Context itself (mx 2.x style).
Context.__enter__ = lambda self: (_push_context(self), self)[1]
Context.__exit__ = lambda self, *a: _pop_context()
