"""``mxnet_tpu.analysis`` — correctness tooling that mechanically
enforces the invariants the rest of the tree hand-maintains
(docs/static_analysis.md):

1. :mod:`~mxnet_tpu.analysis.lockwitness` — a runtime lock-order
   witness in the faults.py zero-cost-when-disabled pattern: project
   locks are constructed through :func:`named_lock` /
   :func:`named_rlock` / :func:`named_condition`, and when enabled the
   witness builds the process lock-ordering graph, flags cycles
   (potential deadlocks) and blocking calls under held locks.
2. :mod:`~mxnet_tpu.analysis.lint` — the AST project linter behind
   ``tools/mxlint.py``: fault sites must be registered, metrics must be
   named and documented, serving/fleet raises must be MXNetError-typed,
   locks must be ``with``-scoped, monotonic-clock convention holds.
3. :mod:`~mxnet_tpu.analysis.raceguard` — static guarded-by race
   detection over the named-lock stack (which attribute belongs to
   which lock; outside-lock accesses, validated annotations/pragmas,
   callbacks-under-lock) plus the guard map
   (``docs/concurrency_contract.json``) that
   ``tools/chaos_sweep.py --corroborate`` cross-checks against the
   witness's acquisition dump.

The lockwitness half is imported eagerly (every lock-owning module
needs the constructors at import); the linter and raceguard load
lazily — they pull in ``ast`` machinery no serving process wants.
"""
from .lockwitness import (LockOrderError, LockWitness, active_witness,
                          disable, enable, known_lock_sites, named_condition,
                          named_lock, named_rlock, note_blocking)

__all__ = [
    "LockOrderError", "LockWitness", "active_witness", "disable",
    "enable", "known_lock_sites", "named_condition", "named_lock",
    "named_rlock", "note_blocking",
    "run_lint", "Finding", "RULES",
    "build_guard_map", "corroborate", "raceguard",
]

_LAZY = {"run_lint": ".lint", "Finding": ".lint", "RULES": ".lint",
         "build_guard_map": ".raceguard", "corroborate": ".raceguard"}


def __getattr__(name):
    if name in ("raceguard", "lint"):      # lazy submodules
        import importlib
        mod = importlib.import_module("." + name, __name__)
        globals()[name] = mod
        return mod
    if name in _LAZY:
        import importlib
        mod = importlib.import_module(_LAZY[name], __name__)
        obj = getattr(mod, name)
        globals()[name] = obj
        return obj
    raise AttributeError(
        f"module 'mxnet_tpu.analysis' has no attribute {name!r}")
