"""Runtime lock-order witness — deadlock potential caught on the
interleavings that actually ran.

Eight PRs grew this codebase 25+ ``threading.Lock``s across the
serving engine, batcher, overload controller, fleet router/replicas,
observability registry/tracer, checkpoint integrity, and the fault
machinery itself.  Their correctness rests on an UNDOCUMENTED partial
order: as long as no two threads ever acquire two of them in opposite
orders, the system cannot deadlock.  Nothing checked that — a PR could
introduce an A→B / B→A inversion that only deadlocks under production
interleavings.  This module is the check, in the faults.py
zero-cost-when-disabled pattern (docs/static_analysis.md):

- Project locks are constructed through :func:`named_lock` /
  :func:`named_rlock` / :func:`named_condition` with a stable *site*
  name (``"serving.engine.cond"``).  **Disabled (the default), these
  return plain ``threading`` primitives** — the witness costs nothing
  you could measure on the serving bench, exactly like a
  :func:`~mxnet_tpu.resilience.faults.inject` site with no plan active.
- Enabled (:func:`enable`, or ``MXTPU_LOCKWITNESS=1`` before import),
  locks come back wrapped: every acquisition pushes onto a per-thread
  held stack, and acquiring B while holding A adds the edge A→B to a
  process-wide lock-ordering graph.  A new edge that closes a cycle is
  a **potential deadlock witnessed on a real interleaving** — recorded
  as a typed finding (or raised as :class:`LockOrderError` with
  ``raise_on_cycle=True``).
- Known blocking points (compiled-program dispatch, ``Future.result``
  waits, ``Condition.wait``) call :func:`note_blocking`; doing so while
  holding any witnessed lock is the *lock-held-across-blocking-call*
  finding — the latency/starvation cousin of a deadlock (a scheduler
  dispatching XLA while holding the admission lock stalls every
  producer for the whole device step).
- Two *different* locks from the same site nested (e.g. two
  ``ReplicaHandle._lock``s) are a ``same_site`` finding: safe only
  under a consistent global order the graph cannot see, so it must be
  either fixed or allowlisted with a justification.

Findings can be allowlisted via ``lockwitness_allowlist.json`` next to
this module — entries carry a mandatory justification and are
validated by ``tools/mxlint.py`` (rule ``lock-allowlist``), so the
escape hatch is itself under static analysis.

``tools/chaos_sweep.py --lockwitness`` runs the whole chaos matrix
under the witness and embeds the graph report; the tier-1 suite run
with ``MXTPU_LOCKWITNESS=1`` is the widest net (numbers recorded in
docs/static_analysis.md).  The static other half is
:mod:`~mxnet_tpu.analysis.raceguard` (which attribute belongs to which
lock); ``chaos_sweep.py --corroborate`` diffs its guard map against
this witness's acquisition dump so the two analyses vouch for each
other.
"""
from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional, Tuple

from ..base import MXNetError

__all__ = ["LockOrderError", "LockWitness", "named_lock", "named_rlock",
           "named_condition", "note_blocking", "enable", "disable",
           "active_witness", "known_lock_sites", "KNOWN_LOCK_SITES",
           "DEFAULT_ALLOWLIST_PATH"]


class LockOrderError(MXNetError):
    """A witnessed lock-order cycle (potential deadlock) or a blocking
    call under a held lock, raised when the witness runs in strict
    mode (``enable(raise_on_cycle=True)``)."""


#: Every lock site ever constructed through this module (site → doc).
#: The static linter cross-checks allowlist entries against the
#: ``named_*`` literals in the tree; this dict is the runtime mirror.
KNOWN_LOCK_SITES: Dict[str, str] = {}

#: The allowlist shipped with the repo — findings with an in-tree
#: justification.  tools/mxlint.py validates its shape and that every
#: referenced site exists.
DEFAULT_ALLOWLIST_PATH = os.path.join(os.path.dirname(__file__),
                                      "lockwitness_allowlist.json")


def known_lock_sites() -> tuple:
    return tuple(sorted(KNOWN_LOCK_SITES))


# The one active witness.  Written under _WITNESS_LOCK; read lock-free
# on hot paths (single-reference torn reads are impossible in CPython).
_ACTIVE: Optional["LockWitness"] = None
_WITNESS_LOCK = threading.Lock()


class _Held:
    """One entry on a thread's held-lock stack."""
    __slots__ = ("site", "obj")

    def __init__(self, site: str, obj):
        self.site = site
        self.obj = obj


class LockWitness:
    """The process-wide ordering graph + finding recorder.

    Nodes are lock *sites* (not instances): every ``ReplicaHandle``
    lock is one node, which is what makes the graph small, stable
    across runs, and meaningful — an inversion between two *classes* of
    lock is the bug, whichever instances exhibited it first.
    """

    def __init__(self, raise_on_cycle: bool = False,
                 allowlist: Optional[List[dict]] = None):
        self.raise_on_cycle = bool(raise_on_cycle)
        self._lock = threading.Lock()      # internal; never witnessed
        self._tls = threading.local()
        # every thread's held stack, keyed by thread id — the fallback
        # for LEGAL cross-thread Lock releases (handoff patterns): the
        # releasing thread must be able to pop the owner's entry or it
        # goes stale and fabricates phantom ordering edges forever
        self._stacks: Dict[int, List[_Held]] = {}
        # site -> set of sites acquired while it was held
        self._graph: Dict[str, set] = {}
        self._seen_keys: set = set()       # finding dedup
        self.findings: List[dict] = []     # surviving findings
        self.allowed: List[dict] = []      # findings the allowlist ate
        self.acquisitions = 0
        self.per_site: Dict[str, int] = {}
        self._allowlist = [
            (e.get("kind"), tuple(sorted(e.get("sites", []))))
            for e in (allowlist or [])]

    # ------------------------------------------------------------- held TLS
    def _held(self) -> List[_Held]:
        h = getattr(self._tls, "held", None)
        if h is None:
            h = self._tls.held = []
            with self._lock:
                self._stacks[threading.get_ident()] = h
        return h

    # ----------------------------------------------------------- recording
    def on_acquired(self, site: str, obj) -> None:
        held = self._held()
        # ALL held-stack access happens under the witness lock: the
        # cross-thread release path scans and mutates OTHER threads'
        # stacks, so even a thread's own stack is shared state
        with self._lock:
            new_edges: List[Tuple[str, str]] = []
            same_site_from = None
            for e in held:
                if e.obj is obj:
                    # reentrant re-acquire of the same RLock: not an edge
                    continue
                if e.site == site:
                    same_site_from = e
                else:
                    new_edges.append((e.site, site))
            held.append(_Held(site, obj))
            self.acquisitions += 1
            self.per_site[site] = self.per_site.get(site, 0) + 1
            if same_site_from is not None:
                self._record("same_site", (site,),
                             f"two distinct {site!r} locks nested in one "
                             f"thread — safe only under a consistent "
                             f"global order the witness cannot verify")
            for a, b in new_edges:
                succ = self._graph.setdefault(a, set())
                if b in succ:
                    continue
                cycle = self._path(b, a)
                succ.add(b)
                if cycle is not None:
                    path = [a] + cycle
                    self._record("cycle", tuple(sorted(set(path))),
                                 "lock-order cycle witnessed: "
                                 + " -> ".join(path))

    def on_released(self, site: str, obj) -> None:
        held = self._held()
        with self._lock:
            for i in range(len(held) - 1, -1, -1):
                if held[i].obj is obj:
                    del held[i]
                    return
            # not held by THIS thread: a cross-thread release
            # (threading.Lock explicitly allows it) — pop the owner's
            # entry so it cannot rot into phantom edges
            for stack in self._stacks.values():
                for i in range(len(stack) - 1, -1, -1):
                    if stack[i].obj is obj:
                        del stack[i]
                        return

    def note_blocking(self, what: str, exclude=None) -> None:
        """A known blocking call is about to run on this thread; any
        witnessed lock still held (minus ``exclude`` — a Condition's
        own lock, which ``wait`` releases) is a finding."""
        held = self._held()
        with self._lock:
            sites = tuple(sorted({e.site for e in held
                                  if e.obj is not exclude}))
            if not sites:
                return
            self._record("blocking", sites + (what,),
                         f"blocking call {what!r} while holding "
                         f"{', '.join(sites)}", sites=list(sites) + [what])

    # caller holds self._lock
    def _record(self, kind: str, key: tuple, detail: str,
                sites: Optional[list] = None):
        dedup = (kind, key)
        if dedup in self._seen_keys:
            return
        self._seen_keys.add(dedup)
        finding = {"kind": kind,
                   "sites": sites if sites is not None else list(key),
                   "detail": detail,
                   "thread": threading.current_thread().name}
        if (kind, tuple(sorted(finding["sites"]))) in self._allowlist:
            self.allowed.append(finding)
            return
        self.findings.append(finding)
        if self.raise_on_cycle and kind == "cycle":
            raise LockOrderError(detail)

    def _path(self, src: str, dst: str) -> Optional[List[str]]:
        """DFS src→dst over the current graph; returns the site path
        (src..dst) or None.  Caller holds self._lock; the graph has
        tens of nodes, so recursion depth is a non-issue."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self._graph.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    # -------------------------------------------------------------- report
    def cycles(self) -> List[dict]:
        with self._lock:
            return [f for f in self.findings if f["kind"] == "cycle"]

    def report(self) -> dict:
        """JSON-able summary: graph size, every edge, findings."""
        with self._lock:
            edges = sorted((a, b) for a, succ in self._graph.items()
                           for b in succ)
            return {
                "nodes": len({s for e in edges for s in e}
                             | set(self._graph)),
                "edges": len(edges),
                "edge_list": [f"{a} -> {b}" for a, b in edges],
                "acquisitions": self.acquisitions,
                "per_site": dict(sorted(self.per_site.items())),
                "findings": list(self.findings),
                "allowed": list(self.allowed),
                "cycles": len([f for f in self.findings
                               if f["kind"] == "cycle"]),
            }


# ------------------------------------------------------------ wrapped locks

class _WitnessedLock:
    """A ``threading.Lock``/``RLock`` wrapper that reports acquisitions
    to the active witness.  Created only while a witness is enabled;
    after ``disable()`` each op degrades to one global load + None
    check on top of the raw primitive."""

    __slots__ = ("site", "_raw")

    def __init__(self, site: str, raw):
        self.site = site
        self._raw = raw

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        # the wrapper IS the lock implementation; callers still go
        # through `with`
        ok = self._raw.acquire(blocking, timeout)  # mxlint: disable=naked-acquire
        if ok:
            w = _ACTIVE
            if w is not None:
                try:
                    w.on_acquired(self.site, self)
                except LockOrderError:
                    # strict mode: the acquisition that completed the
                    # cycle raises — but the RAW lock is already held
                    # and __exit__ will never run, so undo both halves
                    # or the error leaves the lock leaked and a stale
                    # held-stack entry fabricating phantom edges
                    self._raw.release()
                    w.on_released(self.site, self)
                    raise
        return ok

    def release(self) -> None:
        self._raw.release()
        w = _ACTIVE
        if w is not None:
            w.on_released(self.site, self)

    def locked(self) -> bool:
        return self._raw.locked()

    def __enter__(self):
        self.acquire()  # mxlint: disable=naked-acquire
        return self

    def __exit__(self, *exc):
        self.release()

    def __repr__(self):
        return f"<witnessed {self._raw!r} site={self.site!r}>"


class _WitnessedCondition(threading.Condition):
    """``threading.Condition`` over a witnessed lock; ``wait`` is a
    known blocking point (it releases ITS lock but anything else the
    thread holds blocks every peer for the whole wait)."""

    def __init__(self, site: str):
        self.site = site
        super().__init__(lock=_WitnessedLock(site, threading.Lock()))

    def wait(self, timeout: Optional[float] = None):
        w = _ACTIVE
        if w is not None:
            w.note_blocking(f"{self.site}.wait", exclude=self._lock)
        return super().wait(timeout)


def named_lock(site: str, doc: str = ""):
    """A project mutex with a stable site name.  Plain
    ``threading.Lock()`` unless a witness is enabled — the
    zero-cost-when-disabled contract (tested)."""
    KNOWN_LOCK_SITES.setdefault(site, doc)
    if _ACTIVE is None:
        return threading.Lock()
    return _WitnessedLock(site, threading.Lock())


def named_rlock(site: str, doc: str = ""):
    """Reentrant variant of :func:`named_lock` (re-acquiring the same
    instance is never an ordering edge)."""
    KNOWN_LOCK_SITES.setdefault(site, doc)
    if _ACTIVE is None:
        return threading.RLock()
    return _WitnessedLock(site, threading.RLock())


def named_condition(site: str, doc: str = ""):
    """Condition variable variant; its ``wait`` reports as a blocking
    point when other witnessed locks are held."""
    KNOWN_LOCK_SITES.setdefault(site, doc)
    if _ACTIVE is None:
        return threading.Condition()
    return _WitnessedCondition(site)


def note_blocking(what: str) -> None:
    """Hook placed before known blocking calls (engine dispatch,
    ``Future.result`` waits).  Zero-cost when disabled: one global load
    and a None check — keep this the ONLY code on that path."""
    w = _ACTIVE
    if w is not None:
        w.note_blocking(what)


# ------------------------------------------------------------- lifecycle

def load_allowlist(path: Optional[str] = None) -> List[dict]:
    """The in-repo justification file (see module docstring); absent
    file reads as empty."""
    path = path or DEFAULT_ALLOWLIST_PATH
    if not os.path.exists(path):
        return []
    with open(path) as f:
        data = json.load(f)
    entries = data.get("entries", data) if isinstance(data, dict) else data
    if not isinstance(entries, list):
        raise MXNetError(f"lockwitness allowlist {path!r} must hold a "
                         f"list of entries")
    return entries


def enable(raise_on_cycle: bool = False,
           allowlist_path: Optional[str] = None) -> LockWitness:
    """Install (or replace) the process-global witness and return it.
    Only locks constructed AFTER this call are witnessed — enable
    before building engines/routers (the env knob
    ``MXTPU_LOCKWITNESS=1`` does it at import, ahead of everything)."""
    global _ACTIVE
    w = LockWitness(raise_on_cycle=raise_on_cycle,
                    allowlist=load_allowlist(allowlist_path))
    with _WITNESS_LOCK:
        _ACTIVE = w
    return w


def disable() -> Optional[dict]:
    """Deactivate the witness; returns its final report (or None if it
    was not enabled).  Already-wrapped locks stay wrapped but pay only
    the global-load + None check per op afterwards."""
    global _ACTIVE
    with _WITNESS_LOCK:
        w, _ACTIVE = _ACTIVE, None
    return w.report() if w is not None else None


def active_witness() -> Optional[LockWitness]:
    return _ACTIVE


# Env-driven enable: MXTPU_LOCKWITNESS=1 turns the witness on before
# any project lock is constructed (this module is imported by every
# lock-owning module); MXTPU_LOCKWITNESS_OUT=path dumps the report at
# interpreter exit — how the tier-1-under-witness numbers in
# docs/static_analysis.md were recorded.
if os.environ.get("MXTPU_LOCKWITNESS", "") not in ("", "0"):
    enable(raise_on_cycle=os.environ.get("MXTPU_LOCKWITNESS_RAISE", "")
           not in ("", "0"))
    _out = os.environ.get("MXTPU_LOCKWITNESS_OUT", "")
    if _out:
        import atexit

        def _dump(path=_out):
            w = _ACTIVE
            if w is not None:
                with open(path, "w") as f:
                    json.dump(w.report(), f, indent=2, sort_keys=True)

        atexit.register(_dump)
