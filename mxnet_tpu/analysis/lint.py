"""mxlint — the AST project linter behind ``tools/mxlint.py``.

Eight PRs accumulated contracts that nothing checked mechanically:
fault-injection sites are stringly typed, metric names follow an
undocumented convention, the serving/fleet error taxonomy is
hand-maintained, and lock discipline lives in reviewers' heads.  Each
rule here codifies one of those contracts (docs/static_analysis.md has
the catalog with rationale and the how-to-add-a-rule recipe):

``fault-site``
    Every site literal fired through ``inject``/``poison`` (and
    targeted by :class:`FaultPlan` builders) must be declared in
    ``faults.KNOWN_SITES`` via ``register_site`` — a typo'd site is
    silently dead chaos coverage.
``metric-name``
    Every complete ``mxtpu_*`` metric-name literal must match
    ``mxtpu_[a-z0-9_]+`` and appear in the docs/observability.md
    catalog (templated entries like ``mxtpu_serving_<counter>_total``
    match as families) — an undocumented metric is invisible to the
    fleet scraper's dashboards.
``typed-raise``
    No bare ``ValueError``/``RuntimeError``/``KeyError``/``TypeError``/
    ``Exception`` raised inside ``serving/`` or ``fleet/`` — every
    failure a caller can see must be MXNetError-typed
    (docs/serving.md error taxonomy).
``naked-acquire``
    Locks are acquired via ``with``; a bare ``.acquire()`` is allowed
    only when the IMMEDIATELY following statement is a ``try`` whose
    ``finally`` releases the same object — anything else leaks the lock
    on the first exception between acquire and release.
``wall-clock``
    No ``time.time()`` inside the components that follow the
    monotonic-clock convention (``serving``, ``fleet``, ``resilience``,
    ``observability``, ``analysis``) — NTP steps wall clocks backwards,
    which turns deadline/ordering arithmetic into negative durations.
``lock-allowlist``
    The lockwitness allowlist file must be well-formed: known kinds,
    sites that exist (statically collected from ``named_lock``/
    ``named_rlock``/``named_condition``/``note_blocking`` literals),
    and a real justification string per entry — the escape hatch is
    itself under analysis.

Suppression: append ``# mxlint: disable=<rule>[,<rule>...]`` to the
offending line (``disable=all`` silences every rule for that line).
Use sparingly; every pragma is a reviewer conversation.

The linter is PURELY static — it parses source with :mod:`ast` and
never imports the code under analysis, so it runs in CI without jax or
a device."""
from __future__ import annotations

import ast
import json
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

__all__ = ["Finding", "RULES", "run_lint", "collect_files"]

RULES: Dict[str, str] = {
    "fault-site": "fault site literal not registered in faults.KNOWN_SITES",
    "metric-name": "metric literal violates mxtpu_* naming or is missing "
                   "from the docs/observability.md catalog",
    "typed-raise": "untyped exception raised on a serving/fleet path "
                   "(must be MXNetError-typed)",
    "naked-acquire": "lock acquired outside `with` without a matching "
                     "try/finally release",
    "wall-clock": "time.time() used where the monotonic-clock convention "
                  "applies",
    "lock-allowlist": "malformed lockwitness allowlist entry",
}

#: component directories where the monotonic-clock convention applies
WALL_CLOCK_SCOPE = ("serving", "fleet", "resilience", "observability",
                    "analysis")
#: component directories where raises must be MXNetError-typed
TYPED_RAISE_SCOPE = ("serving", "fleet")
#: exception names considered untyped on those paths
UNTYPED_RAISES = ("ValueError", "RuntimeError", "KeyError", "TypeError",
                  "IndexError", "Exception")

#: call names whose first positional string argument is a fault site
FAULT_SITE_CALLS = ("inject", "_inject", "poison", "_poison", "maybe_fire",
                    "_run_step")
#: FaultPlan builder methods whose first argument is a fault site
FAULT_PLAN_BUILDERS = ("raise_at", "delay_at", "kill_at", "call_at",
                       "nonfinite_at", "corrupt_at")
#: lockwitness constructors whose first argument is a lock site
LOCK_SITE_CALLS = ("named_lock", "named_rlock", "named_condition",
                   "_named_lock", "_named_rlock", "_named_condition")

METRIC_RE = re.compile(r"^mxtpu_[a-z0-9_]+$")
_METRIC_DOC_RE = re.compile(r"mxtpu_[a-z0-9_<>]*[a-z0-9_>]")
_PRAGMA_RE = re.compile(r"#\s*mxlint:\s*disable=([a-zA-Z0-9_,\- ]+)")

ALLOWLIST_KINDS = ("cycle", "blocking", "same_site")


class Finding:
    """One lint violation: where, which rule, and why."""

    __slots__ = ("path", "line", "rule", "message")

    def __init__(self, path: str, line: int, rule: str, message: str):
        self.path = path
        self.line = int(line)
        self.rule = rule
        self.message = message

    def __repr__(self):
        return f"{self.path}:{self.line}: {self.rule}: {self.message}"

    def as_dict(self) -> dict:
        return {"path": self.path, "line": self.line, "rule": self.rule,
                "message": self.message}


def collect_files(paths: Sequence[str]) -> List[str]:
    """Expand files/directories into the .py list to lint."""
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs if d != "__pycache__"]
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if f.endswith(".py"))
        else:
            raise FileNotFoundError(p)
    return out


def _component(path: str) -> Optional[str]:
    """The component directory a file lives in (``serving``, ``fleet``,
    …): the segment after the LAST ``mxnet_tpu`` path element — a
    checkout directory itself named ``mxnet_tpu`` must not shadow the
    package root and silently widen/disable the scoped rules."""
    parts = os.path.normpath(path).split(os.sep)
    for i in range(len(parts) - 2, -1, -1):
        if parts[i] == "mxnet_tpu":
            nxt = parts[i + 1]
            return None if nxt.endswith(".py") else nxt
    # fixture trees: treat the immediate parent directory as component
    return parts[-2] if len(parts) >= 2 else None


def _pragmas(source: str) -> Dict[int, Set[str]]:
    """line number → rules disabled on that line."""
    out: Dict[int, Set[str]] = {}
    for i, text in enumerate(source.splitlines(), 1):
        m = _PRAGMA_RE.search(text)
        if m:
            out[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return out


def _str_arg(call: ast.Call) -> Optional[Tuple[str, int]]:
    """The first positional argument if it is a plain string literal."""
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value, call.args[0].lineno
    return None


def _call_name(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


# --------------------------------------------------------- site collection

def collect_registered_fault_sites(trees) -> Set[str]:
    """Every ``register_site("...")`` literal in the scanned tree — the
    static mirror of ``faults.KNOWN_SITES`` (faults.py declares the
    in-tree sites with exactly these calls) — PLUS the in-package
    faults.py registry itself, so a partial lint
    (``mxlint.py mxnet_tpu/serving/engine.py``) that does not scan
    faults.py still knows the real sites instead of flagging every
    legitimate literal."""
    sites: Set[str] = set()
    trees = list(trees)
    faults_py = os.path.normpath(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", "resilience",
        "faults.py"))
    if os.path.exists(faults_py) \
            and not any(os.path.abspath(p) == faults_py
                        for p, _t, _s in trees):
        try:
            with open(faults_py, encoding="utf-8") as f:
                trees.append((faults_py, ast.parse(f.read()), ""))
        except (OSError, SyntaxError):
            pass
    for _path, tree, _src in trees:
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and _call_name(node) == "register_site":
                lit = _str_arg(node)
                if lit:
                    sites.add(lit[0])
    return sites


def collect_lock_sites(trees) -> Set[str]:
    """Every lock/blocking site constructed in the scanned tree:
    ``named_*`` first args (+ their ``.wait`` blocking names) and
    ``note_blocking`` literals."""
    sites: Set[str] = set()
    for _path, tree, _src in trees:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            lit = _str_arg(node)
            if lit is None:
                continue
            if name in LOCK_SITE_CALLS:
                sites.add(lit[0])
                sites.add(lit[0] + ".wait")
            elif name in ("note_blocking", "_note_blocking"):
                sites.add(lit[0])
    return sites


def _doc_catalog(doc_path: Optional[str]):
    """Parse docs/observability.md into (exact-name set, template-regex
    list).  ``mxtpu_serving_<counter>_total`` becomes a family regex."""
    exact: Set[str] = set()
    families: List[re.Pattern] = []
    if not doc_path or not os.path.exists(doc_path):
        return None
    with open(doc_path, encoding="utf-8") as f:
        text = f.read()
    for tok in set(_METRIC_DOC_RE.findall(text)):
        if "<" in tok:
            # templated family: mxtpu_serving_<counter>_total
            pat = re.sub(r"<[a-z0-9_]+>", "[a-z0-9_]+", re.escape(tok))
            families.append(re.compile("^" + pat + "$"))
        else:
            exact.add(tok)
    return exact, families


def _find_repo_root(paths: Sequence[str]) -> Optional[str]:
    """Walk up from the first path to a directory holding docs/."""
    cur = os.path.abspath(paths[0] if paths else ".")
    if os.path.isfile(cur):
        cur = os.path.dirname(cur)
    for _ in range(10):
        if os.path.isdir(os.path.join(cur, "docs")):
            return cur
        nxt = os.path.dirname(cur)
        if nxt == cur:
            return None
        cur = nxt
    return None


# ----------------------------------------------------------------- checks

def _check_fault_sites(path, tree, known: Set[str], findings):
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if name in FAULT_SITE_CALLS or name in FAULT_PLAN_BUILDERS:
            lit = _str_arg(node)
            if lit is None:
                continue            # dynamic site: runtime check owns it
            site, line = lit
            base = site.split("@", 1)[0]
            if base not in known:
                findings.append(Finding(
                    path, line, "fault-site",
                    f"fault site {site!r} is not registered in "
                    f"faults.KNOWN_SITES — a typo'd site is silently "
                    f"dead chaos coverage; declare it with "
                    f"register_site()"))


def _check_metric_names(path, tree, catalog, findings):
    for node in ast.walk(tree):
        if not isinstance(node, ast.Constant) \
                or not isinstance(node.value, str):
            continue
        v = node.value
        # a CANDIDATE metric name: mxtpu_ + word chars only.  Thread
        # names ('mxtpu-digest'), filenames ('mxtpu_io.cc'), prose and
        # prefix fragments ('mxtpu_serving_') are not metric literals.
        if not re.match(r"^mxtpu_[A-Za-z0-9_]+$", v) or v.endswith("_"):
            continue
        if not METRIC_RE.match(v):
            findings.append(Finding(
                path, node.lineno, "metric-name",
                f"metric literal {v!r} violates the mxtpu_[a-z0-9_]+ "
                f"naming convention"))
            continue
        if catalog is None:
            continue
        exact, families = catalog
        if v in exact or any(f.match(v) for f in families):
            continue
        findings.append(Finding(
            path, node.lineno, "metric-name",
            f"metric {v!r} is not in the docs/observability.md catalog "
            f"— undocumented metrics are invisible to fleet dashboards"))


def _check_typed_raises(path, tree, findings):
    comp = _component(path)
    if comp not in TYPED_RAISE_SCOPE:
        return
    for node in ast.walk(tree):
        if not isinstance(node, ast.Raise) or node.exc is None:
            continue
        exc = node.exc
        name = None
        if isinstance(exc, ast.Call) and isinstance(exc.func, ast.Name):
            name = exc.func.id
        elif isinstance(exc, ast.Name):
            name = exc.id
        if name in UNTYPED_RAISES:
            findings.append(Finding(
                path, node.lineno, "typed-raise",
                f"raise {name} on a {comp}/ path — every failure a "
                f"caller can see must be MXNetError-typed "
                f"(docs/serving.md error taxonomy)"))


def _stmt_blocks(tree):
    """Yield every list of sibling statements in the module."""
    for node in ast.walk(tree):
        for field in ("body", "orelse", "finalbody"):
            block = getattr(node, field, None)
            if isinstance(block, list) and block \
                    and isinstance(block[0], ast.stmt):
                yield block
        for handler in getattr(node, "handlers", []) or []:
            if handler.body:
                yield handler.body


def _check_naked_acquire(path, tree, findings):
    acquires = [node for node in ast.walk(tree)
                if isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "acquire"]
    if not acquires:
        return
    # allowed shape: `x.acquire()` / `got = x.acquire(timeout=...)` as a
    # statement whose NEXT sibling is a try whose finally releases the
    # same object (a bounded acquire cannot use `with`, so this is the
    # one blessed non-context form)
    allowed = set()
    for block in _stmt_blocks(tree):
        for i, stmt in enumerate(block):
            if isinstance(stmt, ast.Expr):
                call = stmt.value
            elif isinstance(stmt, ast.Assign):
                call = stmt.value
            else:
                continue
            if not (isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Attribute)
                    and call.func.attr == "acquire"
                    and i + 1 < len(block)
                    and isinstance(block[i + 1], ast.Try)):
                continue
            target = ast.dump(call.func.value)
            for fin in block[i + 1].finalbody:
                for sub in ast.walk(fin):
                    if isinstance(sub, ast.Call) \
                            and isinstance(sub.func, ast.Attribute) \
                            and sub.func.attr == "release" \
                            and ast.dump(sub.func.value) == target:
                        allowed.add(id(stmt.value))
    seen = set()
    for node in acquires:
        key = (node.lineno, node.col_offset)
        if id(node) in allowed or key in seen:
            continue
        seen.add(key)
        findings.append(Finding(
            path, node.lineno, "naked-acquire",
            "lock acquired outside `with` — an exception between "
            "acquire and release leaks the lock; use `with lock:` "
            "(or acquire immediately followed by try/finally "
            "release)"))


def _check_wall_clock(path, tree, findings):
    if _component(path) not in WALL_CLOCK_SCOPE:
        return
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "time" \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "time":
            findings.append(Finding(
                path, node.lineno, "wall-clock",
                "time.time() where the monotonic-clock convention "
                "applies — NTP steps make wall-clock deltas go "
                "negative; use time.monotonic() (or pragma a genuine "
                "epoch timestamp)"))


def check_allowlist(allowlist_path: str, lock_sites: Set[str],
                    findings: List[Finding]) -> None:
    """Validate the lockwitness allowlist file (absent file = nothing
    to validate)."""
    if not os.path.exists(allowlist_path):
        return
    try:
        with open(allowlist_path, encoding="utf-8") as f:
            data = json.load(f)
    except ValueError as e:
        findings.append(Finding(allowlist_path, 1, "lock-allowlist",
                                f"not valid JSON: {e}"))
        return
    entries = data.get("entries") if isinstance(data, dict) else data
    if not isinstance(entries, list):
        findings.append(Finding(
            allowlist_path, 1, "lock-allowlist",
            "expected {\"entries\": [...]} or a top-level list"))
        return
    for i, e in enumerate(entries):
        where = f"entry {i}"
        if not isinstance(e, dict):
            findings.append(Finding(allowlist_path, 1, "lock-allowlist",
                                    f"{where}: not an object"))
            continue
        kind = e.get("kind")
        if kind not in ALLOWLIST_KINDS:
            findings.append(Finding(
                allowlist_path, 1, "lock-allowlist",
                f"{where}: kind must be one of {ALLOWLIST_KINDS}, "
                f"got {kind!r}"))
        sites = e.get("sites")
        if not (isinstance(sites, list) and sites
                and all(isinstance(s, str) for s in sites)):
            findings.append(Finding(
                allowlist_path, 1, "lock-allowlist",
                f"{where}: sites must be a non-empty list of strings"))
            sites = []
        for s in sites:
            if lock_sites and s not in lock_sites:
                findings.append(Finding(
                    allowlist_path, 1, "lock-allowlist",
                    f"{where}: unknown lock/blocking site {s!r} — not "
                    f"constructed anywhere in the linted tree (stale "
                    f"entry after a rename?)"))
        just = e.get("justification", "")
        if not isinstance(just, str) or len(just.strip()) < 20:
            findings.append(Finding(
                allowlist_path, 1, "lock-allowlist",
                f"{where}: justification must explain WHY the finding "
                f"is safe (>= 20 chars), got {just!r}"))


# ------------------------------------------------------------------ driver

def run_lint(paths: Sequence[str],
             doc_catalog_path: Optional[str] = None,
             allowlist_path: Optional[str] = None) -> List[Finding]:
    """Lint ``paths`` (files or directories).  ``doc_catalog_path``
    defaults to ``<repo>/docs/observability.md`` found by walking up
    from the first path; ``allowlist_path`` defaults to the in-package
    ``lockwitness_allowlist.json``.  Returns pragma-filtered findings
    sorted by (path, line)."""
    files = collect_files(paths)
    trees = []
    findings: List[Finding] = []
    for path in files:
        with open(path, encoding="utf-8") as f:
            src = f.read()
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError as e:
            findings.append(Finding(path, e.lineno or 1, "parse",
                                    f"syntax error: {e.msg}"))
            continue
        trees.append((path, tree, src))

    known_sites = collect_registered_fault_sites(trees)
    lock_sites = collect_lock_sites(trees)

    root = _find_repo_root(paths)
    if doc_catalog_path is None and root is not None:
        cand = os.path.join(root, "docs", "observability.md")
        doc_catalog_path = cand if os.path.exists(cand) else None
    catalog = _doc_catalog(doc_catalog_path)

    if allowlist_path is None:
        from .lockwitness import DEFAULT_ALLOWLIST_PATH
        allowlist_path = DEFAULT_ALLOWLIST_PATH
    check_allowlist(allowlist_path, lock_sites, findings)

    for path, tree, src in trees:
        per_file: List[Finding] = []
        _check_fault_sites(path, tree, known_sites, per_file)
        _check_metric_names(path, tree, catalog, per_file)
        _check_typed_raises(path, tree, per_file)
        _check_naked_acquire(path, tree, per_file)
        _check_wall_clock(path, tree, per_file)
        pragmas = _pragmas(src)
        for f in per_file:
            disabled = pragmas.get(f.line, set())
            if f.rule in disabled or "all" in disabled:
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
