"""mxlint — the AST project linter behind ``tools/mxlint.py``.

Nine PRs accumulated contracts that nothing checked mechanically:
fault-injection sites are stringly typed, metric names follow an
undocumented convention, the serving/fleet error taxonomy is
hand-maintained, lock discipline lives in reviewers' heads, and the
named-lock stack's *guarded-by* relation (which attribute belongs to
which lock) was implicit.  Each rule here codifies one of those
contracts (docs/static_analysis.md has the catalog with rationale and
the how-to-add-a-rule recipe):

``fault-site``
    Every site literal fired through ``inject``/``poison`` (and
    targeted by :class:`FaultPlan` builders) must be declared in
    ``faults.KNOWN_SITES`` via ``register_site`` — a typo'd site is
    silently dead chaos coverage.
``metric-name``
    Every complete ``mxtpu_*`` metric-name literal must match
    ``mxtpu_[a-z0-9_]+`` AND appear in the docs/observability.md
    catalog (templated entries like ``mxtpu_serving_<counter>_total``
    match as families) — an undocumented metric is invisible to the
    fleet scraper's dashboards.
``span-name``
    Every complete ``serving.*``/``fleet.*``/``loop.*`` span or
    flight-recorder event-name literal must appear in the
    docs/observability.md taxonomy tables — mirroring ``metric-name``,
    so recording an event and documenting it stay one change (an
    undocumented event is a timeline entry no operator can look up).
``typed-raise``
    No bare ``ValueError``/``RuntimeError``/``KeyError``/``TypeError``/
    ``Exception`` raised inside ``serving/`` or ``fleet/`` — every
    failure a caller can see must be MXNetError-typed
    (docs/serving.md error taxonomy).
``naked-acquire``
    Locks are acquired via ``with``; a bare ``.acquire()`` is allowed
    only when the IMMEDIATELY following statement is a ``try`` whose
    ``finally`` releases the same object — anything else leaks the lock
    on the first exception between acquire and release.
``wall-clock``
    No ``time.time()`` inside the components that follow the
    monotonic-clock convention (``serving``, ``fleet``, ``resilience``,
    ``observability``, ``analysis``) — NTP steps wall clocks backwards,
    which turns deadline/ordering arithmetic into negative durations.
``lock-allowlist``
    The lockwitness allowlist file must be well-formed: known kinds,
    sites that exist (statically collected from ``named_lock``/
    ``named_rlock``/``named_condition``/``note_blocking`` literals),
    and a real justification string per entry — the escape hatch is
    itself under analysis.
``guarded-by`` / ``guard-declare`` / ``callback-under-lock``
    The raceguard pass (:mod:`~mxnet_tpu.analysis.raceguard`): every
    attribute written under a named lock belongs to that lock, and any
    access reached outside it is a statically-detected race; its
    declaration/pragma grammar is validated; and resolving futures or
    invoking user callbacks while a guard is held is flagged as the
    static analogue of the lockwitness ``blocking`` finding.

All rules run over ONE shared parse and ONE node index per file (a
single ``ast.walk``) — adding a rule must not add a tree traversal;
the wall-time contract over the full package is pinned in
``tests/test_analysis.py``.

Suppression: append ``# mxlint: disable=<rule>[,<rule>...]`` to the
offending line (``disable=all`` silences every rule for that line).
The raceguard rules prefer their own *justified* pragmas
(``# raceguard: unguarded(<why>)`` / ``callback-ok(<why>)``) — those
carry a validated >= 20-char justification, so use them instead of the
bare disable.  Use sparingly; every pragma is a reviewer conversation.

The linter is PURELY static — it parses source with :mod:`ast` and
never imports the code under analysis, so it runs in CI without jax or
a device."""
from __future__ import annotations

import ast
import json
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import raceguard as _raceguard

__all__ = ["Finding", "FileIndex", "RULES", "run_lint", "collect_files"]

RULES: Dict[str, str] = {
    "fault-site": "fault site literal not registered in faults.KNOWN_SITES",
    "metric-name": "metric literal violates mxtpu_* naming or is missing "
                   "from the docs/observability.md catalog",
    "span-name": "span/flight-recorder event name literal missing from "
                 "the docs/observability.md taxonomy tables",
    "typed-raise": "untyped exception raised on a serving/fleet path "
                   "(must be MXNetError-typed)",
    "naked-acquire": "lock acquired outside `with` without a matching "
                     "try/finally release",
    "wall-clock": "time.time() used where the monotonic-clock convention "
                  "applies",
    "lock-allowlist": "malformed lockwitness allowlist entry",
}
RULES.update(_raceguard.RACEGUARD_RULES)

#: component directories where the monotonic-clock convention applies
WALL_CLOCK_SCOPE = ("serving", "fleet", "resilience", "observability",
                    "analysis", "data")
#: component directories where raises must be MXNetError-typed
TYPED_RAISE_SCOPE = ("serving", "fleet", "data")
#: exception names considered untyped on those paths
UNTYPED_RAISES = ("ValueError", "RuntimeError", "KeyError", "TypeError",
                  "IndexError", "Exception")

#: call names whose first positional string argument is a fault site
FAULT_SITE_CALLS = ("inject", "_inject", "poison", "_poison", "maybe_fire",
                    "_run_step")
#: FaultPlan builder methods whose first argument is a fault site
FAULT_PLAN_BUILDERS = ("raise_at", "delay_at", "kill_at", "call_at",
                       "nonfinite_at", "corrupt_at")
#: lockwitness constructors whose first argument is a lock site
LOCK_SITE_CALLS = ("named_lock", "named_rlock", "named_condition",
                   "_named_lock", "_named_rlock", "_named_condition")

#: call names whose first positional string argument is a span or
#: flight-recorder event name (Tracer.span/record_span/event,
#: FlightRecorder.record/trigger/dump, ``tr.event``-style wrappers) —
#: the span-name rule only fires when that argument ALSO matches
#: _SPAN_NAME_RE, so e.g. ``autograd.record()`` (no args) and
#: ``metrics.span("prefill")`` (bare phase word) are never candidates
SPAN_NAME_CALLS = ("span", "record_span", "event", "record", "trigger",
                   "dump")
#: a COMPLETE span/event name in the enforced namespaces — the same
#: components whose fault sites and error taxonomy are already linted
_SPAN_NAME_RE = re.compile(r"^(?:serving|fleet|loop)\.[a-z0-9_]+$")
#: backticked span/event tokens in the docs taxonomy tables
_SPAN_DOC_RE = re.compile(r"`((?:serving|fleet|loop)\.[a-z0-9_]+)`")

METRIC_RE = re.compile(r"^mxtpu_[a-z0-9_]+$")
_METRIC_DOC_RE = re.compile(r"mxtpu_[a-z0-9_<>]*[a-z0-9_>]")
_PRAGMA_RE = re.compile(r"#\s*mxlint:\s*disable=([a-zA-Z0-9_,\- ]+)")

ALLOWLIST_KINDS = ("cycle", "blocking", "same_site")

#: statement-list owners the FileIndex collects blocks from
#: (``except*`` arrives in 3.11; ``match`` cases are handled apart)
_BLOCK_NODES = (ast.Module, ast.FunctionDef, ast.AsyncFunctionDef,
                ast.ClassDef, ast.With, ast.AsyncWith, ast.If, ast.While,
                ast.For, ast.AsyncFor, ast.Try) + (
                    (ast.TryStar,) if hasattr(ast, "TryStar") else ())


class Finding:
    """One lint violation: where, which rule, and why."""

    __slots__ = ("path", "line", "rule", "message")

    def __init__(self, path: str, line: int, rule: str, message: str):
        self.path = path
        self.line = int(line)
        self.rule = rule
        self.message = message

    def __repr__(self):
        return f"{self.path}:{self.line}: {self.rule}: {self.message}"

    def as_dict(self) -> dict:
        return {"path": self.path, "line": self.line, "rule": self.rule,
                "message": self.message}


def collect_files(paths: Sequence[str]) -> List[str]:
    """Expand files/directories into the .py list to lint."""
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs if d != "__pycache__"]
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if f.endswith(".py"))
        else:
            raise FileNotFoundError(p)
    return out


def _component(path: str) -> Optional[str]:
    """The component directory a file lives in (``serving``, ``fleet``,
    …): the segment after the LAST ``mxnet_tpu`` path element — a
    checkout directory itself named ``mxnet_tpu`` must not shadow the
    package root and silently widen/disable the scoped rules."""
    parts = os.path.normpath(path).split(os.sep)
    for i in range(len(parts) - 2, -1, -1):
        if parts[i] == "mxnet_tpu":
            nxt = parts[i + 1]
            return None if nxt.endswith(".py") else nxt
    # fixture trees: treat the immediate parent directory as component
    return parts[-2] if len(parts) >= 2 else None


def _pragmas(source: str) -> Dict[int, Set[str]]:
    """line number → rules disabled on that line."""
    out: Dict[int, Set[str]] = {}
    for i, text in enumerate(source.splitlines(), 1):
        m = _PRAGMA_RE.search(text)
        if m:
            out[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return out


def _str_arg(call: ast.Call) -> Optional[Tuple[str, int]]:
    """The first positional argument if it is a plain string literal."""
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value, call.args[0].lineno
    return None


def _call_name(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


# ------------------------------------------------------- shared file index

class FileIndex:
    """One parse + ONE ``ast.walk`` per file; every rule reads the node
    lists it needs from here instead of re-walking the tree.  The
    raceguard pass shares ``tree``/``source`` (its class-structured
    traversal is not expressible as flat node lists, but it re-parses
    nothing)."""

    __slots__ = ("path", "tree", "source", "component", "pragmas",
                 "calls", "str_constants", "raises", "blocks")

    def __init__(self, path: str, tree: ast.Module, source: str):
        self.path = path
        self.tree = tree
        self.source = source
        self.component = _component(path)
        self.pragmas = _pragmas(source)
        calls: List[ast.Call] = []
        consts: List[ast.Constant] = []
        raises: List[ast.Raise] = []
        blocks: List[List[ast.stmt]] = []
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                calls.append(node)
            elif isinstance(node, ast.Constant) \
                    and isinstance(node.value, str):
                consts.append(node)
            elif isinstance(node, ast.Raise):
                raises.append(node)
            if isinstance(node, _BLOCK_NODES):
                for field in ("body", "orelse", "finalbody"):
                    block = getattr(node, field, None)
                    if isinstance(block, list) and block \
                            and isinstance(block[0], ast.stmt):
                        blocks.append(block)
                for handler in getattr(node, "handlers", []) or []:
                    if handler.body:
                        blocks.append(handler.body)
            elif isinstance(node, ast.Match):
                for case in node.cases:
                    if case.body:
                        blocks.append(case.body)
        self.calls = calls
        self.str_constants = consts
        self.raises = raises
        self.blocks = blocks


# --------------------------------------------------------- site collection

def collect_registered_fault_sites(indexes: Sequence[FileIndex]) -> Set[str]:
    """Every ``register_site("...")`` literal in the scanned tree — the
    static mirror of ``faults.KNOWN_SITES`` (faults.py declares the
    in-tree sites with exactly these calls) — PLUS the in-package
    faults.py registry itself, so a partial lint
    (``mxlint.py mxnet_tpu/serving/engine.py``) that does not scan
    faults.py still knows the real sites instead of flagging every
    legitimate literal."""
    sites: Set[str] = set()
    call_lists = [idx.calls for idx in indexes]
    faults_py = os.path.normpath(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", "resilience",
        "faults.py"))
    if os.path.exists(faults_py) \
            and not any(os.path.abspath(idx.path) == faults_py
                        for idx in indexes):
        try:
            with open(faults_py, encoding="utf-8") as f:
                tree = ast.parse(f.read())
            call_lists.append([n for n in ast.walk(tree)
                               if isinstance(n, ast.Call)])
        except (OSError, SyntaxError):
            pass
    for calls in call_lists:
        for node in calls:
            if _call_name(node) == "register_site":
                lit = _str_arg(node)
                if lit:
                    sites.add(lit[0])
    return sites


def collect_lock_sites(indexes: Sequence[FileIndex]) -> Set[str]:
    """Every lock/blocking site constructed in the scanned tree:
    ``named_*`` first args (+ their ``.wait`` blocking names) and
    ``note_blocking`` literals."""
    sites: Set[str] = set()
    for idx in indexes:
        for node in idx.calls:
            name = _call_name(node)
            lit = _str_arg(node)
            if lit is None:
                continue
            if name in LOCK_SITE_CALLS:
                sites.add(lit[0])
                sites.add(lit[0] + ".wait")
            elif name in ("note_blocking", "_note_blocking"):
                sites.add(lit[0])
    return sites


def _doc_span_catalog(doc_path: Optional[str]) -> Optional[Set[str]]:
    """Every backticked ``serving.*``/``fleet.*``/``loop.*`` token in
    docs/observability.md — the span/event taxonomy the ``span-name``
    rule enforces.  Recording an event and documenting it are one
    change, mirroring the metric-name rule."""
    if not doc_path or not os.path.exists(doc_path):
        return None
    with open(doc_path, encoding="utf-8") as f:
        return set(_SPAN_DOC_RE.findall(f.read()))


def _doc_catalog(doc_path: Optional[str]):
    """Parse docs/observability.md into (exact-name set, template-regex
    list).  ``mxtpu_serving_<counter>_total`` becomes a family regex."""
    exact: Set[str] = set()
    families: List[re.Pattern] = []
    if not doc_path or not os.path.exists(doc_path):
        return None
    with open(doc_path, encoding="utf-8") as f:
        text = f.read()
    for tok in set(_METRIC_DOC_RE.findall(text)):
        if "<" in tok:
            # templated family: mxtpu_serving_<counter>_total
            pat = re.sub(r"<[a-z0-9_]+>", "[a-z0-9_]+", re.escape(tok))
            families.append(re.compile("^" + pat + "$"))
        else:
            exact.add(tok)
    return exact, families


def _find_repo_root(paths: Sequence[str]) -> Optional[str]:
    """Walk up from the first path to a directory holding docs/."""
    cur = os.path.abspath(paths[0] if paths else ".")
    if os.path.isfile(cur):
        cur = os.path.dirname(cur)
    for _ in range(10):
        if os.path.isdir(os.path.join(cur, "docs")):
            return cur
        nxt = os.path.dirname(cur)
        if nxt == cur:
            return None
        cur = nxt
    return None


# ----------------------------------------------------------------- checks

def _check_fault_sites(idx: FileIndex, known: Set[str], findings):
    for node in idx.calls:
        name = _call_name(node)
        if name in FAULT_SITE_CALLS or name in FAULT_PLAN_BUILDERS:
            lit = _str_arg(node)
            if lit is None:
                continue            # dynamic site: runtime check owns it
            site, line = lit
            base = site.split("@", 1)[0]
            if base not in known:
                findings.append(Finding(
                    idx.path, line, "fault-site",
                    f"fault site {site!r} is not registered in "
                    f"faults.KNOWN_SITES — a typo'd site is silently "
                    f"dead chaos coverage; declare it with "
                    f"register_site()"))


def _check_metric_names(idx: FileIndex, catalog, findings):
    for node in idx.str_constants:
        v = node.value
        # a CANDIDATE metric name: mxtpu_ + word chars only.  Thread
        # names ('mxtpu-digest'), filenames ('mxtpu_io.cc'), prose and
        # prefix fragments ('mxtpu_serving_') are not metric literals.
        if not re.match(r"^mxtpu_[A-Za-z0-9_]+$", v) or v.endswith("_"):
            continue
        if not METRIC_RE.match(v):
            findings.append(Finding(
                idx.path, node.lineno, "metric-name",
                f"metric literal {v!r} violates the mxtpu_[a-z0-9_]+ "
                f"naming convention"))
            continue
        if catalog is None:
            continue
        exact, families = catalog
        if v in exact or any(f.match(v) for f in families):
            continue
        findings.append(Finding(
            idx.path, node.lineno, "metric-name",
            f"metric {v!r} is not in the docs/observability.md catalog "
            f"— undocumented metrics are invisible to fleet dashboards"))


def _check_span_names(idx: FileIndex, span_catalog: Optional[Set[str]],
                      findings):
    """``span-name`` (docs/static_analysis.md): a COMPLETE
    ``serving.*``/``fleet.*``/``loop.*`` literal passed as the span or
    flight-recorder event name must appear in the
    docs/observability.md taxonomy tables — an undocumented event is a
    timeline entry (or a flight-bundle trigger) no operator can look
    up at 3am.  Dynamic names and names outside the three enforced
    namespaces are the runtime's problem, not this rule's."""
    if span_catalog is None:
        return
    for node in idx.calls:
        if _call_name(node) not in SPAN_NAME_CALLS:
            continue
        lit = _str_arg(node)
        if lit is None:
            continue
        name, line = lit
        if not _SPAN_NAME_RE.match(name):
            continue
        if name in span_catalog:
            continue
        findings.append(Finding(
            idx.path, line, "span-name",
            f"span/event name {name!r} is not in the "
            f"docs/observability.md taxonomy tables — record an event "
            f"and document it in one change (backtick it in a taxonomy "
            f"row)"))


def _check_typed_raises(idx: FileIndex, findings):
    comp = idx.component
    if comp not in TYPED_RAISE_SCOPE:
        return
    for node in idx.raises:
        if node.exc is None:
            continue
        exc = node.exc
        name = None
        if isinstance(exc, ast.Call) and isinstance(exc.func, ast.Name):
            name = exc.func.id
        elif isinstance(exc, ast.Name):
            name = exc.id
        if name in UNTYPED_RAISES:
            findings.append(Finding(
                idx.path, node.lineno, "typed-raise",
                f"raise {name} on a {comp}/ path — every failure a "
                f"caller can see must be MXNetError-typed "
                f"(docs/serving.md error taxonomy)"))


def _check_naked_acquire(idx: FileIndex, findings):
    acquires = [node for node in idx.calls
                if isinstance(node.func, ast.Attribute)
                and node.func.attr == "acquire"]
    if not acquires:
        return
    # allowed shape: `x.acquire()` / `got = x.acquire(timeout=...)` as a
    # statement whose NEXT sibling is a try whose finally releases the
    # same object (a bounded acquire cannot use `with`, so this is the
    # one blessed non-context form)
    allowed = set()
    for block in idx.blocks:
        for i, stmt in enumerate(block):
            if isinstance(stmt, ast.Expr):
                call = stmt.value
            elif isinstance(stmt, ast.Assign):
                call = stmt.value
            else:
                continue
            if not (isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Attribute)
                    and call.func.attr == "acquire"
                    and i + 1 < len(block)
                    and isinstance(block[i + 1], ast.Try)):
                continue
            target = ast.dump(call.func.value)
            for fin in block[i + 1].finalbody:
                for sub in ast.walk(fin):
                    if isinstance(sub, ast.Call) \
                            and isinstance(sub.func, ast.Attribute) \
                            and sub.func.attr == "release" \
                            and ast.dump(sub.func.value) == target:
                        allowed.add(id(stmt.value))
    seen = set()
    for node in acquires:
        key = (node.lineno, node.col_offset)
        if id(node) in allowed or key in seen:
            continue
        seen.add(key)
        findings.append(Finding(
            idx.path, node.lineno, "naked-acquire",
            "lock acquired outside `with` — an exception between "
            "acquire and release leaks the lock; use `with lock:` "
            "(or acquire immediately followed by try/finally "
            "release)"))


def _check_wall_clock(idx: FileIndex, findings):
    if idx.component not in WALL_CLOCK_SCOPE:
        return
    for node in idx.calls:
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "time" \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "time":
            findings.append(Finding(
                idx.path, node.lineno, "wall-clock",
                "time.time() where the monotonic-clock convention "
                "applies — NTP steps make wall-clock deltas go "
                "negative; use time.monotonic() (or pragma a genuine "
                "epoch timestamp)"))


def _check_raceguard(idx: FileIndex, findings):
    """The guarded-by pass (docs/static_analysis.md): shares this
    file's parse; its own justified pragmas are applied inside the
    pass, the central ``mxlint: disable`` filter applies after."""
    mod = _raceguard.analyze_module(idx.path, idx.tree, idx.source)
    for r in mod.findings:
        findings.append(Finding(idx.path, r.line, r.rule, r.message))


def check_allowlist(allowlist_path: str, lock_sites: Set[str],
                    findings: List[Finding]) -> None:
    """Validate the lockwitness allowlist file (absent file = nothing
    to validate)."""
    if not os.path.exists(allowlist_path):
        return
    try:
        with open(allowlist_path, encoding="utf-8") as f:
            data = json.load(f)
    except ValueError as e:
        findings.append(Finding(allowlist_path, 1, "lock-allowlist",
                                f"not valid JSON: {e}"))
        return
    entries = data.get("entries") if isinstance(data, dict) else data
    if not isinstance(entries, list):
        findings.append(Finding(
            allowlist_path, 1, "lock-allowlist",
            "expected {\"entries\": [...]} or a top-level list"))
        return
    for i, e in enumerate(entries):
        where = f"entry {i}"
        if not isinstance(e, dict):
            findings.append(Finding(allowlist_path, 1, "lock-allowlist",
                                    f"{where}: not an object"))
            continue
        kind = e.get("kind")
        if kind not in ALLOWLIST_KINDS:
            findings.append(Finding(
                allowlist_path, 1, "lock-allowlist",
                f"{where}: kind must be one of {ALLOWLIST_KINDS}, "
                f"got {kind!r}"))
        sites = e.get("sites")
        if not (isinstance(sites, list) and sites
                and all(isinstance(s, str) for s in sites)):
            findings.append(Finding(
                allowlist_path, 1, "lock-allowlist",
                f"{where}: sites must be a non-empty list of strings"))
            sites = []
        for s in sites:
            if lock_sites and s not in lock_sites:
                findings.append(Finding(
                    allowlist_path, 1, "lock-allowlist",
                    f"{where}: unknown lock/blocking site {s!r} — not "
                    f"constructed anywhere in the linted tree (stale "
                    f"entry after a rename?)"))
        just = e.get("justification", "")
        if not isinstance(just, str) or len(just.strip()) < 20:
            findings.append(Finding(
                allowlist_path, 1, "lock-allowlist",
                f"{where}: justification must explain WHY the finding "
                f"is safe (>= 20 chars), got {just!r}"))


# ------------------------------------------------------------------ driver

def run_lint(paths: Sequence[str],
             doc_catalog_path: Optional[str] = None,
             allowlist_path: Optional[str] = None) -> List[Finding]:
    """Lint ``paths`` (files or directories).  ``doc_catalog_path``
    defaults to ``<repo>/docs/observability.md`` found by walking up
    from the first path; ``allowlist_path`` defaults to the in-package
    ``lockwitness_allowlist.json``.  Returns pragma-filtered findings
    sorted by (path, line)."""
    files = collect_files(paths)
    indexes: List[FileIndex] = []
    findings: List[Finding] = []
    for path in files:
        with open(path, encoding="utf-8") as f:
            src = f.read()
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError as e:
            findings.append(Finding(path, e.lineno or 1, "parse",
                                    f"syntax error: {e.msg}"))
            continue
        indexes.append(FileIndex(path, tree, src))

    known_sites = collect_registered_fault_sites(indexes)
    lock_sites = collect_lock_sites(indexes)

    root = _find_repo_root(paths)
    if doc_catalog_path is None and root is not None:
        cand = os.path.join(root, "docs", "observability.md")
        doc_catalog_path = cand if os.path.exists(cand) else None
    catalog = _doc_catalog(doc_catalog_path)
    span_catalog = _doc_span_catalog(doc_catalog_path)

    if allowlist_path is None:
        from .lockwitness import DEFAULT_ALLOWLIST_PATH
        allowlist_path = DEFAULT_ALLOWLIST_PATH
    check_allowlist(allowlist_path, lock_sites, findings)

    for idx in indexes:
        per_file: List[Finding] = []
        _check_fault_sites(idx, known_sites, per_file)
        _check_metric_names(idx, catalog, per_file)
        _check_span_names(idx, span_catalog, per_file)
        _check_typed_raises(idx, per_file)
        _check_naked_acquire(idx, per_file)
        _check_wall_clock(idx, per_file)
        _check_raceguard(idx, per_file)
        for f in per_file:
            disabled = idx.pragmas.get(f.line, set())
            if f.rule in disabled or "all" in disabled:
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
