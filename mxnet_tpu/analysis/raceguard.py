"""raceguard — static guarded-by race detection for the named-lock
stack, cross-checked against the runtime lock witness.

PR 9 made lock *ordering* mechanical (mxlint + lockwitness), but
nothing checked *which shared state each lock actually guards*: an
attribute read outside its lock compiles, passes tier-1, and corrupts
stats or scheduling only under real concurrency.  That property is
statically decidable for exactly the disciplined, ``with``-scoped
locking style mxlint already mandates — the same observation behind
classic lockset analysis (Eraser) and annotation checking (Clang
``-Wthread-safety`` / ``GuardedBy``), cited here as prior art by name.

The pass is purely static (:mod:`ast`, never imports the code under
analysis) and runs per class:

1. **Guard binding** — every ``self._x = named_lock/named_rlock/
   named_condition("site")`` (anywhere in the assigned expression, so
   ``self._cond = cond or _named_condition(...)`` binds too) makes
   ``_x`` a *guard* with a stable lock site.
2. **Guarded-set inference** — any ``self.attr`` *written* (attribute
   store, augmented store, or a subscript/del store whose base is the
   attribute) while a guard is lexically held, in a non-``__init__``
   method, marks ``attr`` guarded by the guards held at locked writes.
   An attribute written under several guards at different sites is
   satisfied by any one of them (pin it down with an explicit
   declaration if that is too permissive).
3. **Access checking** — every read or write of a guarded attribute
   reached while none of its guards is held is a ``guarded-by``
   finding.  ``__init__`` is exempt end to end: pre-publication state
   is thread-private by construction.
4. **Declarations** — ``# guarded-by: _lock`` widens inference:

   - on a ``self.attr = ...`` line it declares the attribute guarded
     (even if no locked write exists for the inference to see);
   - on a ``def`` line it declares a caller-holds-lock contract: the
     whole method body is analyzed as if ``_lock`` were held (the
     static mirror of a "caller holds self._lock" comment).

   A declaration naming a non-guard, or floating on a line that is
   neither of the above, is a ``guard-declare`` finding.
5. **Escape hatch** — ``# raceguard: unguarded(<justification>)`` on
   the offending line suppresses its ``guarded-by`` findings, and
   ``# raceguard: callback-ok(<justification>)`` its
   ``callback-under-lock`` findings.  Justifications are VALIDATED
   (>= 20 chars) — a bare pragma is itself a ``guard-declare``
   finding, exactly like the lockwitness allowlist's mandatory
   justification.
6. **callback-under-lock** — resolving a future (``set_result`` /
   ``set_exception`` / ``add_done_callback``) or invoking a
   user-supplied callback (``callback``/``cb``/``*_callback``) while
   a guard is held runs arbitrary foreign code — waiter wake-ups and
   re-entrant calls — inside the critical section: the static
   analogue of the witness's ``blocking`` finding.

Known approximations (the runtime witness covers the dynamics):
the analysis is lexical, so a ``Condition.wait`` (which releases its
lock mid-block) still counts as held; accesses to *other* objects'
guarded attributes are out of scope (only ``self.`` accesses are
checked); nested ``def``/``lambda`` bodies reset the held set — a
closure created under a lock usually runs after it is released — and
can re-enter via their own ``guarded-by:`` declaration.

The static↔dynamic loop closes through the **guard map**
(:func:`build_guard_map`): lock site → guarded attributes for every
``named_*`` construction in the tree, class- or module-scoped.  It is
checked in as ``docs/concurrency_contract.json`` (drift-tested), and
``tools/chaos_sweep.py --corroborate`` diffs it against the witness's
acquisition dump so every statically-claimed guard is proven exercised
and every witnessed site statically mapped (docs/static_analysis.md).
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

__all__ = ["RACEGUARD_RULES", "GuardBinding", "ModuleGuards",
           "analyze_module", "build_guard_map", "CALLBACK_METHODS",
           "CORROBORATION_EXEMPT", "GUARD_MAP_SCHEMA_VERSION"]

RACEGUARD_RULES: Dict[str, str] = {
    "guarded-by": "guarded attribute accessed outside its lock",
    "guard-declare": "malformed guarded-by declaration or raceguard "
                     "pragma (unknown guard, orphan line, missing or "
                     "short justification)",
    "callback-under-lock": "future resolution / user callback invoked "
                           "while a guard is held",
}

#: constructors that make an attribute a guard (site = first str arg)
GUARD_CTORS: Dict[str, str] = {
    "named_lock": "lock", "_named_lock": "lock",
    "named_rlock": "rlock", "_named_rlock": "rlock",
    "named_condition": "condition", "_named_condition": "condition",
}

#: method names that resolve a future — foreign code (waiter wake-ups,
#: done-callbacks) runs inside them
CALLBACK_METHODS = ("set_result", "set_exception", "add_done_callback")
#: callable names treated as user-supplied callbacks
_CALLBACK_NAMES = ("callback", "cb")
_CALLBACK_SUFFIX = "_callback"

#: guard-map sites a chaos sweep cannot legitimately exercise, with the
#: mandatory justification (>= 20 chars, tested) — the corroboration
#: analogue of the lockwitness allowlist
CORROBORATION_EXEMPT: Dict[str, str] = {
    "native.build": "acquired only while compiling the optional native "
                    "IO helper from source; the chaos host has no "
                    "toolchain contract, so the sweep must not require "
                    "a C compiler to pass",
}

GUARD_MAP_SCHEMA_VERSION = 1

#: try-shaped statements (``except*`` arrives in 3.11)
_TRY_TYPES = (ast.Try,) + ((ast.TryStar,)
                           if hasattr(ast, "TryStar") else ())

_DECL_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)\s*$")
_PRAGMA_RE = re.compile(r"#\s*raceguard:\s*([A-Za-z_-]+)\s*\((.*)\)")
_PRAGMA_ANY_RE = re.compile(r"#\s*raceguard:")
_PRAGMA_VERBS = ("unguarded", "callback-ok")
_MIN_JUSTIFICATION = 20


class GuardBinding:
    """One guard: a named lock bound to a class attribute or a module
    global, plus the attribute set inferred/declared as guarded by it
    (class scope only — module globals are mapped for corroboration
    but not access-checked; the hot-path convention there is the
    documented lock-free published read)."""

    __slots__ = ("site", "kind", "guard", "scope", "line", "attributes")

    def __init__(self, site: str, kind: str, guard: str, scope: str,
                 line: int):
        self.site = site
        self.kind = kind            # lock | rlock | condition
        self.guard = guard          # attribute or global name
        self.scope = scope          # class name, or "" for module scope
        self.line = int(line)
        self.attributes: Set[str] = set()

    def as_dict(self) -> dict:
        return {"guard": self.guard, "kind": self.kind,
                "scope": self.scope or "module",
                "attributes": sorted(self.attributes)}

    def __repr__(self):
        where = self.scope or "module"
        return (f"<guard {self.guard!r} site={self.site!r} {where} "
                f"attrs={sorted(self.attributes)}>")


class _Access:
    __slots__ = ("attr", "write", "line", "held", "in_init")

    def __init__(self, attr, write, line, held, in_init):
        self.attr = attr
        self.write = write
        self.line = line
        self.held = held            # FrozenSet[str] of guard attrs
        self.in_init = in_init


class _Raw:
    """A raw finding before lint.py wraps it in its Finding class (the
    two modules share one parsed tree per file, and lint owns pragma
    filtering + the public type)."""

    __slots__ = ("line", "rule", "message")

    def __init__(self, line: int, rule: str, message: str):
        self.line = int(line)
        self.rule = rule
        self.message = message


def _named_ctor_site(expr: ast.AST) -> Optional[Tuple[str, str]]:
    """(site, kind) if the expression contains a named_* constructor
    call with a literal site anywhere in its subtree."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            f = node.func
            name = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else None)
            if name in GUARD_CTORS and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                return node.args[0].value, GUARD_CTORS[name]
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _subscript_base_attr(node: ast.Subscript) -> Optional[str]:
    """``self.d[k]`` / ``self.d[k][j]`` → ``d`` (the store mutates the
    object the attribute publishes, so it counts as a write of the
    attribute for inference and checking)."""
    v = node.value
    while isinstance(v, ast.Subscript):
        v = v.value
    return _self_attr(v)


def _callback_name(call: ast.Call) -> Optional[str]:
    """The callback-ish name a call invokes, or None."""
    f = call.func
    name = None
    if isinstance(f, ast.Name):
        name = f.id
    elif isinstance(f, ast.Attribute):
        name = f.attr
    if name is None:
        return None
    if name in CALLBACK_METHODS or name in _CALLBACK_NAMES \
            or (name.endswith(_CALLBACK_SUFFIX)
                and name != _CALLBACK_SUFFIX):
        return name
    return None


def _comments(source: str) -> Dict[int, str]:
    """line → real comment text, via :mod:`tokenize` — a pragma quoted
    inside a docstring or an error-message literal (this module is full
    of them) must not count as an annotation."""
    import io
    import tokenize
    out: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass                    # ast parsed it; best-effort comments
    return out


def _source_annotations(source: str):
    """(declarations, pragmas, raw_findings): line → guard name for
    ``# guarded-by:``, line → {verb} for VALID ``# raceguard:`` pragmas,
    plus guard-declare findings for malformed/under-justified ones."""
    decls: Dict[int, str] = {}
    pragmas: Dict[int, Set[str]] = {}
    raw: List[_Raw] = []
    if "guarded-by:" not in source and "raceguard:" not in source:
        return decls, pragmas, raw
    for i, text in sorted(_comments(source).items()):
        m = _DECL_RE.search(text)
        if m:
            decls[i] = m.group(1)
        if not _PRAGMA_ANY_RE.search(text):
            continue
        pm = _PRAGMA_RE.search(text)
        if pm is None:
            raw.append(_Raw(
                i, "guard-declare",
                "malformed raceguard pragma — expected "
                "'# raceguard: unguarded(<justification>)' or "
                "'# raceguard: callback-ok(<justification>)'"))
            continue
        verb, justification = pm.group(1), pm.group(2).strip()
        if verb not in _PRAGMA_VERBS:
            raw.append(_Raw(
                i, "guard-declare",
                f"unknown raceguard pragma verb {verb!r} — valid verbs: "
                f"{', '.join(_PRAGMA_VERBS)}"))
            continue
        if len(justification) < _MIN_JUSTIFICATION:
            raw.append(_Raw(
                i, "guard-declare",
                f"raceguard pragma justification must explain WHY the "
                f"access is safe (>= {_MIN_JUSTIFICATION} chars), got "
                f"{justification!r}"))
            continue
        pragmas.setdefault(i, set()).add(verb)
    return decls, pragmas, raw


# -------------------------------------------------------------- class pass

class _ClassAnalyzer:
    """Two sub-passes over one ClassDef: record every access with its
    lexically-held guard set, then infer the guarded set and emit
    findings.  The traversal tracks:

    - ``with self._g:`` blocks (any number of items, aliased or not);
    - the blessed bounded-acquire form (``got = self._g.acquire(...)``
      immediately followed by ``try``) — its try/else/finally bodies
      count as held, mirroring mxlint's ``naked-acquire`` contract;
    - nested functions/lambdas, which RESET the held set (a closure
      built under a lock usually runs after release) unless their
      ``def`` line carries a ``guarded-by:`` declaration;
    - reentrant re-``with`` of the same guard (RLock style), which is
      naturally idempotent in a lexical set.
    """

    def __init__(self, cls: ast.ClassDef, decls: Dict[int, str],
                 findings: List[_Raw]):
        self.cls = cls
        self.decls = decls
        self.findings = findings
        self.guards: Dict[str, GuardBinding] = {}
        self.accesses: List[_Access] = []
        self.calls: List[Tuple[str, int, FrozenSet[str]]] = []
        self.decl_used: Set[int] = set()
        self._in_init = False

    # ---- pass 1: bind guards + attach declarations
    def bind(self) -> None:
        for meth in self._methods():
            for stmt in ast.walk(meth):
                if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = stmt.targets if isinstance(stmt, ast.Assign) \
                    else [stmt.target]
                if stmt.value is None:
                    continue
                found = _named_ctor_site(stmt.value)
                if found is None:
                    continue
                site, kind = found
                for t in targets:
                    attr = _self_attr(t)
                    if attr is not None and attr not in self.guards:
                        self.guards[attr] = GuardBinding(
                            site, kind, attr, self.cls.name, stmt.lineno)

    def _methods(self):
        for node in self.cls.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node

    # ---- pass 2: record accesses under the lexical held set
    def record(self) -> None:
        for meth in self._methods():
            self._in_init = meth.name == "__init__"
            held = self._decl_held(meth.lineno, frozenset())
            self._walk_body(meth.body, held)

    def _decl_held(self, line: int,
                   base: FrozenSet[str]) -> FrozenSet[str]:
        """Apply a ``guarded-by:`` declaration sitting on a def line."""
        g = self.decls.get(line)
        if g is None:
            return base
        self.decl_used.add(line)
        if g not in self.guards:
            self.findings.append(_Raw(
                line, "guard-declare",
                f"guarded-by declaration names {g!r}, which is not a "
                f"named-lock guard of class {self.cls.name} "
                f"(known guards: {sorted(self.guards) or 'none'})"))
            return base
        return base | {g}

    def _bounded_acquire_guard(self, stmt: ast.stmt) -> Optional[str]:
        """``got = self._g.acquire(...)`` (or bare expression form) —
        the one blessed non-``with`` acquire (see mxlint
        ``naked-acquire``)."""
        value = stmt.value if isinstance(stmt, (ast.Assign, ast.Expr)) \
            else None
        if isinstance(value, ast.Call) \
                and isinstance(value.func, ast.Attribute) \
                and value.func.attr == "acquire":
            attr = _self_attr(value.func.value)
            if attr in self.guards:
                return attr
        return None

    def _walk_body(self, stmts: Sequence[ast.stmt],
                   held: FrozenSet[str]) -> None:
        i = 0
        while i < len(stmts):
            stmt = stmts[i]
            g = self._bounded_acquire_guard(stmt)
            if g is not None and i + 1 < len(stmts) \
                    and isinstance(stmts[i + 1], _TRY_TYPES):
                self._visit_stmt(stmt, held)
                t = stmts[i + 1]
                inner = held | {g}
                self._walk_body(t.body, inner)
                for h in t.handlers:
                    self._walk_body(h.body, inner)
                self._walk_body(t.orelse, inner)
                self._walk_body(t.finalbody, inner)
                i += 2
                continue
            self._visit_stmt(stmt, held)
            i += 1

    def _with_guards(self, node) -> FrozenSet[str]:
        got: Set[str] = set()
        for item in node.items:
            attr = _self_attr(item.context_expr)
            if attr in self.guards:
                got.add(attr)
        return frozenset(got)

    def _visit_stmt(self, stmt: ast.stmt, held: FrozenSet[str]) -> None:
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._visit_expr(item.context_expr, held)
                if item.optional_vars is not None:
                    self._visit_expr(item.optional_vars, held)
            self._walk_body(stmt.body, held | self._with_guards(stmt))
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in stmt.decorator_list:
                self._visit_expr(dec, held)
            inner = self._decl_held(stmt.lineno, frozenset())
            self._walk_body(stmt.body, inner)
            return
        if isinstance(stmt, ast.ClassDef):
            return                        # nested class: its own world
        if isinstance(stmt, _TRY_TYPES):
            self._walk_body(stmt.body, held)
            for h in stmt.handlers:
                if h.type is not None:
                    self._visit_expr(h.type, held)
                self._walk_body(h.body, held)
            self._walk_body(stmt.orelse, held)
            self._walk_body(stmt.finalbody, held)
            return
        if isinstance(stmt, ast.Match):
            self._visit_expr(stmt.subject, held)
            for case in stmt.cases:
                if case.guard is not None:
                    self._visit_expr(case.guard, held)
                self._walk_body(case.body, held)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._visit_expr(stmt.test, held)
            self._walk_body(stmt.body, held)
            self._walk_body(stmt.orelse, held)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._visit_expr(stmt.target, held)
            self._visit_expr(stmt.iter, held)
            self._walk_body(stmt.body, held)
            self._walk_body(stmt.orelse, held)
            return
        # leaf statement: visit every contained expression
        for field in ast.iter_child_nodes(stmt):
            self._visit_expr(field, held)

    def _visit_expr(self, node: ast.AST, held: FrozenSet[str]) -> None:
        if isinstance(node, ast.Lambda):
            self._visit_expr(node.body, frozenset())
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            self._visit_stmt(node, held)
            return
        attr = _self_attr(node)
        if attr is not None:
            write = isinstance(node.ctx, (ast.Store, ast.Del))
            self.accesses.append(_Access(attr, write, node.lineno, held,
                                         self._in_init))
        if isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, (ast.Store, ast.Del)):
            base = _subscript_base_attr(node)
            if base is not None:
                self.accesses.append(_Access(base, True, node.lineno,
                                             held, self._in_init))
        if isinstance(node, ast.Call):
            cb = _callback_name(node)
            if cb is not None and held:
                self.calls.append((cb, node.lineno, held))
        for child in ast.iter_child_nodes(node):
            self._visit_expr(child, held)

    # ---- pass 3: infer + check
    def infer(self, attr_decls: Dict[str, Tuple[str, int]]) -> None:
        """``attr_decls``: attr → (guard, decl line) from ``guarded-by``
        declarations on ``self.attr = ...`` lines of this class."""
        inferred: Dict[str, Set[str]] = {}
        for a in self.accesses:
            if a.write and not a.in_init and a.held \
                    and a.attr not in self.guards:
                inferred.setdefault(a.attr, set()).update(a.held)
        for attr, (guard, line) in attr_decls.items():
            if guard not in self.guards:
                self.findings.append(_Raw(
                    line, "guard-declare",
                    f"guarded-by declaration names {guard!r}, which is "
                    f"not a named-lock guard of class {self.cls.name} "
                    f"(known guards: {sorted(self.guards) or 'none'})"))
                continue
            inferred[attr] = {guard}      # explicit beats inferred
        self.guarded: Dict[str, Set[str]] = inferred
        for attr, gs in inferred.items():
            for g in gs:
                self.guards[g].attributes.add(attr)

    def check(self) -> None:
        seen: Set[Tuple[int, str, str]] = set()
        for a in self.accesses:
            if a.in_init or a.attr not in self.guarded:
                continue
            guards = self.guarded[a.attr]
            if a.held & guards:
                continue
            key = (a.line, a.attr, "w" if a.write else "r")
            if key in seen:
                continue
            seen.add(key)
            glist = ", ".join(
                f"self.{g} ({self.guards[g].site})"
                for g in sorted(guards))
            kind = "write to" if a.write else "read of"
            self.findings.append(_Raw(
                a.line, "guarded-by",
                f"{kind} self.{a.attr} outside its guard — "
                f"{self.cls.name}.{a.attr} is guarded by {glist}; hold "
                f"the lock, declare a caller-holds contract with "
                f"'# guarded-by: <guard>' on the def, or justify with "
                f"'# raceguard: unguarded(<why>)'"))
        for cb, line, held in self.calls:
            sites = ", ".join(
                f"self.{g} ({self.guards[g].site})" for g in sorted(held))
            self.findings.append(_Raw(
                line, "callback-under-lock",
                f"{cb}() invoked while holding {sites} — future "
                f"resolution / user callbacks run foreign code inside "
                f"the critical section (the static analogue of the "
                f"witness's 'blocking' finding); resolve outside the "
                f"lock or justify with "
                f"'# raceguard: callback-ok(<why>)'"))


# ------------------------------------------------------------- module pass

class ModuleGuards:
    """Everything raceguard learned about one module: the per-class and
    module-level guard bindings (for the guard map) and the raw
    findings (for the linter)."""

    __slots__ = ("path", "bindings", "findings")

    def __init__(self, path: str):
        self.path = path
        self.bindings: List[GuardBinding] = []
        self.findings: List[_Raw] = []


def _module_pass(tree: ast.Module, out: ModuleGuards) -> None:
    """Module-level guards: ``_LOCK = named_lock(...)`` at top level,
    guarding the globals written under ``with _LOCK:`` in module
    functions.  Mapped for corroboration; not access-checked — the
    module-global pattern here is deliberately lock-free on read paths
    (single-reference published reads)."""
    guards: Dict[str, GuardBinding] = {}
    module_names: Set[str] = set()
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            module_names.add(stmt.targets[0].id)
            found = _named_ctor_site(stmt.value)
            if found is not None:
                site, kind = found
                name = stmt.targets[0].id
                guards.setdefault(name, GuardBinding(
                    site, kind, name, "", stmt.lineno))
        elif isinstance(stmt, ast.AnnAssign) \
                and isinstance(stmt.target, ast.Name):
            module_names.add(stmt.target.id)
    if not guards:
        return

    def scan(body, held: FrozenSet[str], globals_declared: Set[str]):
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                gd = set(globals_declared)
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Global):
                        gd.update(sub.names)
                scan(stmt.body, frozenset(), gd)
            elif isinstance(stmt, ast.ClassDef):
                # methods may take MODULE locks too (e.g. a plan
                # registering itself under the module's swap lock) —
                # scan them for module-global writes; self.* state is
                # the class pass's job
                scan(stmt.body, frozenset(), globals_declared)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                got = {item.context_expr.id for item in stmt.items
                       if isinstance(item.context_expr, ast.Name)
                       and item.context_expr.id in guards}
                scan(stmt.body, held | frozenset(got), globals_declared)
            elif isinstance(stmt, _TRY_TYPES):
                scan(stmt.body, held, globals_declared)
                for h in stmt.handlers:
                    scan(h.body, held, globals_declared)
                scan(stmt.orelse, held, globals_declared)
                scan(stmt.finalbody, held, globals_declared)
            elif isinstance(stmt, ast.Match):
                for case in stmt.cases:
                    scan(case.body, held, globals_declared)
            elif isinstance(stmt, (ast.If, ast.While, ast.For,
                                   ast.AsyncFor)):
                scan(stmt.body, held, globals_declared)
                scan(stmt.orelse, held, globals_declared)
            elif held:
                writable = globals_declared | module_names
                for node in ast.walk(stmt):
                    name = None
                    if isinstance(node, ast.Name) \
                            and isinstance(node.ctx,
                                           (ast.Store, ast.Del)) \
                            and node.id in globals_declared:
                        name = node.id
                    elif isinstance(node, ast.Subscript) \
                            and isinstance(node.ctx,
                                           (ast.Store, ast.Del)):
                        base = node.value
                        while isinstance(base, ast.Subscript):
                            base = base.value
                        if isinstance(base, ast.Name) \
                                and base.id in writable:
                            name = base.id
                    if name is not None:
                        for g in held:
                            guards[g].attributes.add(name)

    scan(tree.body, frozenset(), set())
    out.bindings.extend(guards.values())


def analyze_module(path: str, tree: ast.Module,
                   source: str) -> ModuleGuards:
    """Run the whole raceguard pass over one already-parsed module.
    Called by ``lint.run_lint`` on the shared per-file parse; usable
    standalone for the guard map."""
    out = ModuleGuards(path)
    decls, pragmas, raw = _source_annotations(source)
    out.findings.extend(raw)

    decl_lines_used: Set[int] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        ca = _ClassAnalyzer(node, decls, out.findings)
        ca.bind()
        ca.record()
        # attribute-level declarations: a `# guarded-by:` on a
        # `self.attr = ...` line inside this class
        attr_decls: Dict[str, Tuple[str, int]] = {}
        for meth in ca._methods():
            for stmt in ast.walk(meth):
                if isinstance(stmt, (ast.Assign, ast.AnnAssign)) \
                        and stmt.lineno in decls:
                    targets = stmt.targets \
                        if isinstance(stmt, ast.Assign) else [stmt.target]
                    for t in targets:
                        attr = _self_attr(t)
                        if attr is not None:
                            attr_decls[attr] = (decls[stmt.lineno],
                                                stmt.lineno)
                            decl_lines_used.add(stmt.lineno)
        decl_lines_used.update(ca.decl_used)
        ca.infer(attr_decls)
        ca.check()
        out.bindings.extend(ca.guards.values())

    _module_pass(tree, out)

    for line, guard in decls.items():
        if line not in decl_lines_used:
            out.findings.append(_Raw(
                line, "guard-declare",
                f"orphan guarded-by declaration ({guard!r}) — it must "
                f"sit on a 'self.attr = ...' assignment or a 'def' line "
                f"inside a class with named-lock guards"))

    # pragma suppression: a VALIDATED pragma eats its rule's findings
    # on that line (invalid pragmas never suppress — they are findings)
    kept: List[_Raw] = []
    for f in out.findings:
        verbs = pragmas.get(f.line, set())
        if f.rule == "guarded-by" and "unguarded" in verbs:
            continue
        if f.rule == "callback-under-lock" and "callback-ok" in verbs:
            continue
        kept.append(f)
    out.findings = kept
    return out


# --------------------------------------------------------------- guard map

def build_guard_map(paths: Sequence[str],
                    root: Optional[str] = None) -> dict:
    """The static concurrency contract: every named-lock site in
    ``paths`` → its bindings (module, scope, guard, kind, guarded
    attributes).  Deterministic (sorted keys/lists, forward-slash
    relative module paths) so the checked-in copy
    (``docs/concurrency_contract.json``) regenerates byte-identical.

    ``root`` anchors the relative module paths; default is the common
    parent of ``paths``."""
    from .lint import collect_files      # lint imports us lazily; safe
    files = collect_files(paths)
    if root is None:
        dirs = [p if os.path.isdir(p) else os.path.dirname(p)
                for p in paths]
        root = os.path.commonpath([os.path.abspath(d) for d in dirs]) \
            if dirs else os.getcwd()
    sites: Dict[str, List[dict]] = {}
    for path in files:
        with open(path, encoding="utf-8") as f:
            src = f.read()
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError:
            continue                      # the linter reports it
        mod = analyze_module(path, tree, src)
        rel = os.path.relpath(os.path.abspath(path),
                              os.path.abspath(root)).replace(os.sep, "/")
        for b in mod.bindings:
            d = b.as_dict()
            d["module"] = rel
            sites.setdefault(b.site, []).append(d)
    return {
        "schema_version": GUARD_MAP_SCHEMA_VERSION,
        "generated_by": "mxnet_tpu.analysis.raceguard.build_guard_map",
        "sites": {
            site: {"bindings": sorted(
                bindings,
                key=lambda d: (d["module"], d["scope"], d["guard"]))}
            for site, bindings in sorted(sites.items())
        },
    }


def corroborate(guard_map: dict, per_site: Dict[str, int],
                exempt: Optional[Dict[str, str]] = None) -> dict:
    """Diff the static guard map against a witness acquisition dump
    (``LockWitness.report()["per_site"]``).  Returns a JSON-able verdict:

    - ``unexercised``: sites the map claims but the run never acquired
      (minus justified :data:`CORROBORATION_EXEMPT` entries) — a guard
      nobody locks is an unproven contract;
    - ``unmapped``: sites the witness acquired that the map does not
      know — runtime locks the static analysis cannot see (a dynamic
      site name, or a module the map build skipped).

    ``passed`` iff both lists are empty."""
    exempt = CORROBORATION_EXEMPT if exempt is None else exempt
    mapped = set(guard_map.get("sites", {}))
    witnessed = {s for s, n in per_site.items() if n > 0}
    unexercised = sorted(mapped - witnessed - set(exempt))
    unmapped = sorted(witnessed - mapped)
    return {
        "passed": not unexercised and not unmapped,
        "mapped_sites": len(mapped),
        "witnessed_sites": len(witnessed),
        "unexercised": unexercised,
        "unmapped": unmapped,
        "exempt": {s: j for s, j in sorted(exempt.items())
                   if s in mapped},
        "acquisitions_per_mapped_site": {
            s: per_site.get(s, 0) for s in sorted(mapped)},
    }
