"""BERT encoder + pretraining heads (parity target: BASELINE config 3 /
GluonNLP bert; MXNet kept BERT in GluonNLP, out of tree, over the contrib
fused attention ops — here it is in-tree on the TP/SP transformer blocks).
"""
from __future__ import annotations

from ..gluon.block import HybridBlock
from ..gluon.nn import Dense, Dropout, Embedding, LayerNorm
from ..ndarray import ops as F
from ..parallel.sharding import annotate
from .transformer import TransformerEncoderLayer

_CONFIGS = {
    "bert_base": (12, 768, 12),
    "bert_large": (24, 1024, 16),
}


class BERTModel(HybridBlock):
    """tokens (B,T), token_types (B,T) → sequence output (B,T,units),
    pooled output (B,units)."""

    def __init__(self, vocab_size=30522, units=768, num_layers=12,
                 num_heads=12, max_length=512, type_vocab_size=2,
                 dropout=0.1, layer_norm_eps=1e-12, scan_layers=None,
                 remat=False, **kwargs):
        super().__init__(**kwargs)
        self._scan_layers = scan_layers
        self._remat = remat
        self._units = units
        self.vocab_size = vocab_size
        self.max_length = max_length
        self.word_embed = Embedding(vocab_size, units)
        annotate(self.word_embed.weight, "vocab", "embed")
        self.token_type_embed = Embedding(type_vocab_size, units)
        self.position_embed = Embedding(max_length, units)
        annotate(self.position_embed.weight, "seq", "embed")
        self.embed_ln = LayerNorm(epsilon=layer_norm_eps, in_channels=units)
        self.embed_drop = Dropout(dropout) if dropout else None
        self.layers = []
        for i in range(num_layers):
            layer = TransformerEncoderLayer(
                units, 4 * units, num_heads, dropout=dropout,
                layer_norm_eps=layer_norm_eps)
            self.register_child(layer, f"layer{i}")
            self.layers.append(layer)
        self.pooler = Dense(units, activation="tanh", flatten=False,
                            in_units=units)

    def forward(self, tokens, token_types=None, valid_length=None):
        b, t = tokens.shape
        if isinstance(t, int) and t > self.max_length:
            raise ValueError(
                f"sequence length {t} exceeds max_length={self.max_length} "
                "(position table size)")
        pos = F.arange_like(tokens, axis=1).astype("int32")
        x = self.word_embed(tokens) + self.position_embed(pos)
        if token_types is not None:
            x = x + self.token_type_embed(token_types)
        x = self.embed_ln(x)
        if self.embed_drop is not None:
            x = self.embed_drop(x)
        mask = None
        if valid_length is not None:
            # (B, 1, 1, T) key-side padding mask
            steps = F.arange_like(tokens, axis=1)
            mask = (steps.reshape((1, 1, 1, t)) <
                    valid_length.reshape((b, 1, 1, 1)))
        from .transformer import run_blocks
        x = run_blocks(self.layers, x, mask, scan=self._scan_layers,
                       remat=self._remat)
        pooled = self.pooler(F.slice_axis(x, axis=1, begin=0, end=1)
                             .reshape((b, self._units)))
        return x, pooled


class BERTForPretrain(HybridBlock):
    """MLM + NSP heads (GluonNLP BERTForPretrain parity)."""

    def __init__(self, backbone: BERTModel, **kwargs):
        super().__init__(**kwargs)
        self.backbone = backbone
        units = backbone._units
        self.mlm_dense = Dense(units, activation="gelu", flatten=False,
                               in_units=units)
        self.mlm_ln = LayerNorm(in_channels=units)
        self.nsp = Dense(2, flatten=False, in_units=units)

    def forward(self, tokens, token_types=None, valid_length=None,
                masked_positions=None):
        seq, pooled = self.backbone(tokens, token_types, valid_length)
        if masked_positions is not None:
            seq = _gather_positions(seq, masked_positions)
        h = self.mlm_ln(self.mlm_dense(seq))
        mlm_logits = F.FullyConnected(
            h, self.backbone.word_embed.weight.data(), None,
            num_hidden=self.backbone.vocab_size, no_bias=True, flatten=False)
        nsp_logits = self.nsp(pooled)
        return mlm_logits, nsp_logits


def _gather_positions(seq, positions):
    """(B, T, U) gathered at (B, M) per-row positions → (B, M, U)."""
    import jax.numpy as jnp

    from ..ndarray.ops import _as_nd, invoke

    def f(x, pos):
        return jnp.take_along_axis(x, pos[:, :, None].astype(jnp.int32),
                                   axis=1)

    return invoke("gather_positions", f, [seq, _as_nd(positions)])


def get_bert(name="bert_base", **kwargs):
    layers, units, heads = _CONFIGS[name]
    cfg = dict(units=units, num_layers=layers, num_heads=heads)
    cfg.update(kwargs)
    return BERTModel(**cfg)
