"""GPT-2 language model — the flagship model of the framework.

Capability add over the reference (SURVEY.md §5.7 / BASELINE config 5:
long-sequence GPT-2): MXNet had no in-tree GPT; this one is built from the
TP/SP-aware transformer blocks, with tied embeddings (vocab-parallel logits)
and flash attention on TPU.
"""
from __future__ import annotations

from ..gluon.block import HybridBlock
from ..gluon.nn import Dropout, Embedding, LayerNorm
from ..ndarray import ops as F
from ..parallel.sharding import annotate
from .. import parallel as _par
from .transformer import TransformerBlock

_CONFIGS = {
    # name: (layers, units, heads)
    "gpt2_124m": (12, 768, 12),
    "gpt2_355m": (24, 1024, 16),
    "gpt2_774m": (36, 1280, 20),
    "gpt2_1558m": (48, 1600, 25),
}


class GPT2Model(HybridBlock):
    """Decoder-only LM: tokens (B, T) int32 → logits (B, T, vocab)."""

    def __init__(self, vocab_size=50257, units=768, num_layers=12,
                 num_heads=12, max_length=1024, dropout=0.1,
                 layer_norm_eps=1e-5, num_experts=0, moe_every=2,
                 moe_top_k=2, moe_capacity_factor=1.25, scan_layers=None,
                 remat=False, **kwargs):
        super().__init__(**kwargs)
        self._scan_layers = scan_layers
        self._remat = remat
        self._units = units
        self.vocab_size = vocab_size
        self.max_length = max_length
        self.wte = Embedding(vocab_size, units)
        annotate(self.wte.weight, "vocab", "embed")
        self.wpe = Embedding(max_length, units)
        annotate(self.wpe.weight, "seq", "embed")
        self.drop = Dropout(dropout) if dropout else None
        self.blocks = []
        for i in range(num_layers):
            if num_experts and i % moe_every == moe_every - 1:
                from .moe import MoETransformerBlock
                blk = MoETransformerBlock(
                    units, 4 * units, num_heads, num_experts,
                    top_k=moe_top_k, capacity_factor=moe_capacity_factor,
                    dropout=dropout, causal=True,
                    layer_norm_eps=layer_norm_eps)
            else:
                blk = TransformerBlock(units, 4 * units, num_heads,
                                       dropout=dropout, causal=True,
                                       layer_norm_eps=layer_norm_eps)
            self.register_child(blk, f"h{i}")
            self.blocks.append(blk)
        self.ln_f = LayerNorm(epsilon=layer_norm_eps, in_channels=units)

    def forward(self, tokens):
        b, t = tokens.shape
        if isinstance(t, int) and t > self.max_length:
            raise ValueError(
                f"sequence length {t} exceeds max_length={self.max_length} "
                "(position table size)")
        pos = F.arange_like(tokens, axis=1).astype("int32")
        x = self.wte(tokens) + self.wpe(pos)
        x = _par.with_sharding_constraint(x, "batch", "seq", None)
        if self.drop is not None:
            x = self.drop(x)
        from .transformer import run_blocks
        x = run_blocks(self.blocks, x, scan=self._scan_layers,
                       remat=self._remat)
        x = self.ln_f(x)
        # tied lm head: logits = x · wteᵀ (vocab-parallel over tp)
        logits = F.FullyConnected(x, self.wte.weight.data(), None,
                                  num_hidden=self.vocab_size, no_bias=True,
                                  flatten=False)
        return _par.with_sharding_constraint(logits, "batch", "seq", "vocab")


def gpt2_lm_loss(logits, labels, aux_weight=0.01):
    """Next-token cross entropy; labels (B, T) already shifted.  Any MoE
    router aux losses recorded during the forward are drained and added
    (weight 0 cost for dense models — the collector is simply empty)."""
    from .moe import pop_aux_losses
    # nll = logsumexp(logits) - logits[label]: skips materializing the full
    # (B, T, V) log_softmax in f32 — the logsumexp reduction reads logits
    # once and the gather is O(B*T) (HBM matters: V=50k dominates activations)
    lse = F.logsumexp(logits, axis=-1)
    picked = F.pick(logits, labels, axis=-1)
    nll = lse - picked
    loss = nll.mean()
    for aux in pop_aux_losses():
        loss = loss + aux * aux_weight
    return loss


def get_gpt2(name="gpt2_124m", **kwargs):
    layers, units, heads = _CONFIGS[name]
    cfg = dict(units=units, num_layers=layers, num_heads=heads)
    cfg.update(kwargs)
    return GPT2Model(**cfg)
