"""GPT-2 language model — the flagship model of the framework.

Capability add over the reference (SURVEY.md §5.7 / BASELINE config 5:
long-sequence GPT-2): MXNet had no in-tree GPT; this one is built from the
TP/SP-aware transformer blocks, with tied embeddings (vocab-parallel logits)
and flash attention on TPU.
"""
from __future__ import annotations

from ..gluon.block import HybridBlock
from ..gluon.nn import Dropout, Embedding, LayerNorm
from ..ndarray import ops as F
from ..parallel.sharding import annotate
from .. import parallel as _par
from .transformer import TransformerBlock

_CONFIGS = {
    # name: (layers, units, heads)
    "gpt2_124m": (12, 768, 12),
    "gpt2_355m": (24, 1024, 16),
    "gpt2_774m": (36, 1280, 20),
    "gpt2_1558m": (48, 1600, 25),
}


def _dense_blocks_only(net):
    from .transformer import TransformerBlock
    if any(type(b) is not TransformerBlock for b in net.blocks):
        raise ValueError(
            "incremental decoding supports dense GPT-2 blocks only "
            "(MoE routing is a training-time layout)")


class _GPT2Decoding:
    """KV-cache incremental decoding mixin surface for GPT2Model."""

    def kv_heads(self):
        """(num_heads, head_dim) of the serving KV caches — the axes a
        GSPMD serving mesh shards (docs/serving.md "Sharded decode"):
        ``num_heads`` must divide evenly over the mesh's model axis."""
        blk0 = self.blocks[0]
        return blk0.attn._num_heads, blk0.attn._head_dim

    def init_cache(self, batch, max_length=None, dtype=None):
        """Per-layer KV caches (B, Tmax, H, D), zero-filled.  Cache dtype
        follows the parameters (bf16 params → bf16 cache, half the HBM)
        unless overridden."""
        import jax.numpy as jnp

        _dense_blocks_only(self)
        t = max_length or self.max_length
        blk0 = self.blocks[0]
        h = blk0.attn._num_heads
        d = blk0.attn._head_dim
        dt = dtype or self.wte.weight.data().jax.dtype
        if dt not in (jnp.bfloat16, jnp.float16, jnp.float32, jnp.float64):
            dt = jnp.float32
        return [{"k": jnp.zeros((batch, t, h, d), dt),
                 "v": jnp.zeros((batch, t, h, d), dt)}
                for _ in self.blocks]

    def prefill(self, tokens_nd, caches):
        """Batched cache fill over the prompt (B,Tp): ONE causal forward
        writes every layer's K/V for positions [0,Tp) and returns the
        last position's logits (B, vocab)."""
        pos = F.arange_like(tokens_nd, axis=1).astype("int32")
        x = self.wte(tokens_nd) + self.wpe(pos)
        new_caches = []
        for blk, cache in zip(self.blocks, caches):
            x, c = blk.forward_prefill(x, cache)
            new_caches.append(c)
        x = self.ln_f(x)
        last = F.slice_axis(x, axis=1, begin=-1, end=None)
        logits = F.FullyConnected(last, self.wte.weight.data(), None,
                                  num_hidden=self.vocab_size, no_bias=True,
                                  flatten=False)
        return logits.reshape((tokens_nd.shape[0], self.vocab_size)), \
            new_caches

    def forward_step(self, tok, caches, idx):
        """One decode position: tok (B,1) int32 at position ``idx`` →
        (logits (B, vocab), new caches).  Inference mode assumed."""
        pos = tok * 0 + idx          # (B,1) int32, traced position
        x = self.wte(tok) + self.wpe(pos)
        new_caches = []
        for blk, cache in zip(self.blocks, caches):
            x, c = blk.forward_step(x, cache, idx)
            new_caches.append(c)
        x = self.ln_f(x)
        logits = F.FullyConnected(x, self.wte.weight.data(), None,
                                  num_hidden=self.vocab_size, no_bias=True,
                                  flatten=False)
        return logits.reshape((tok.shape[0], self.vocab_size)), new_caches

    # ---------------------------------------------------- serving entries
    # The single-step decode surface mxnet_tpu.serving.InferenceEngine
    # drives: a persistent SLOT-batched KV cache (row = in-flight request),
    # bucketed admission prefill, and a per-slot-position decode step —
    # continuous batching of requests at different positions.

    def init_slot_cache(self, num_slots, max_length=None, dtype=None):
        """Persistent serving cache: per-layer (S, Tmax, H, D) where row s
        belongs to whichever request currently owns slot s."""
        _dense_blocks_only(self)
        return self.init_cache(num_slots, max_length, dtype)

    def init_page_cache(self, num_pages, page_size, dtype=None,
                        kv_quant=None):
        """Persistent PAGED serving cache (docs/serving.md "Paged KV"):
        per-layer (N, ps, H, D) where each of the N fixed-size pages
        holds ``page_size`` positions of whichever slot's page table
        currently maps it (the engine reserves the last page as
        scratch).  Structurally this is :meth:`init_cache` with pages
        as the batch dim and the page as the sequence.

        ``kv_quant='int8'`` (docs/serving.md "Quantized KV") stores the
        pages int8 with per-position-per-head fp32 scales as extra
        ``k_scale``/``v_scale`` leaves shaped (N, ps, H, 1) — rank-4
        like every cache leaf, with heads on the same axis, so the
        scales shard, scatter, scrub, export, and digest exactly like
        page payload.  ~3.8x less KV HBM per token at D=64 (1 byte +
        4/D scale bytes per element vs 4)."""
        import jax.numpy as jnp

        _dense_blocks_only(self)
        if kv_quant is None:
            return self.init_cache(num_pages, page_size, dtype)
        if kv_quant != "int8":
            raise ValueError(f"kv_quant={kv_quant!r}: only 'int8' (or "
                             f"None for the float layout) is supported")
        blk0 = self.blocks[0]
        h, d = blk0.attn._num_heads, blk0.attn._head_dim
        return [{"k": jnp.zeros((num_pages, page_size, h, d), jnp.int8),
                 "k_scale": jnp.zeros((num_pages, page_size, h, 1),
                                      jnp.float32),
                 "v": jnp.zeros((num_pages, page_size, h, d), jnp.int8),
                 "v_scale": jnp.zeros((num_pages, page_size, h, 1),
                                      jnp.float32)}
                for _ in self.blocks]

    def prefill_slots(self, tokens_nd, lens, caches, slot_idx,
                      offset=None, page_table=None, paged_kernel=False):
        """Admission prefill for a bucketed batch of prompts: tokens
        (B, Tb) int32 right-PADDED to the bucket length, ``lens`` (B,)
        true lengths, ``slot_idx`` (B,) destination rows of the (R,...)
        caches.  One causal forward writes every layer's K/V for
        positions [0, Tb) into the requests' slots and returns the
        logits at each row's LAST REAL position (B, vocab) — right
        padding never leaks into them (causal mask), and the garbage
        K/V it leaves beyond ``lens`` is overwritten by decode before
        it can be attended.

        With ``offset`` (B,) int32 given, row i's tokens are a CHUNK of
        its prompt starting at absolute position ``offset[i]``: K/V land
        at ``[offset[i], offset[i]+Tb)`` behind the already-populated
        ``[0, offset[i])`` region (earlier chunks / a prefix-cache
        copy), position embeddings follow the absolute positions, and
        attention runs against the full cache row (see
        ``MultiHeadAttention.forward_prefill_slots``).  Logits are
        still at each row's last real CHUNK position ``lens[i]-1`` —
        only the final chunk's logits are meaningful.

        With ``page_table`` (S+1, P) int32 given the caches are PAGED
        — per-layer (N+1, ps, H, D) from :meth:`init_page_cache` — and
        every K/V write/read routes through the table (docs/serving.md
        "Paged KV"); everything else is identical."""
        import jax.numpy as jnp

        from ..ndarray import NDArray

        b = tokens_nd.shape[0]
        if offset is None:
            pos = F.arange_like(tokens_nd, axis=1).astype("int32")
        else:
            t = tokens_nd.shape[1]
            apos = offset[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]
            # clamp the embedding lookup only: padding columns of a final
            # chunk can run past the position table; their K/V writes are
            # OOB scatters (dropped) and their logits are never read
            pos = NDArray(jnp.minimum(apos, self.max_length - 1))
        x = self.wte(tokens_nd) + self.wpe(pos)
        new_caches = []
        for blk, cache in zip(self.blocks, caches):
            x, c = blk.forward_prefill_slots(x, cache, slot_idx, offset,
                                             page_table, paged_kernel)
            new_caches.append(c)
        x = self.ln_f(x)
        last = NDArray(x.jax[jnp.arange(b), lens - 1])      # (B, U)
        logits = F.FullyConnected(last, self.wte.weight.data(), None,
                                  num_hidden=self.vocab_size, no_bias=True,
                                  flatten=False)
        return logits, new_caches

    def decode_step(self, tok, caches, pos, page_table=None,
                    paged_kernel=False):
        """One continuous-batching decode step over EVERY slot: tok (S,)
        int32 NDArray of last tokens, ``pos`` (S,) int32 jax array of
        their (per-slot) positions → (logits (S, vocab), new caches).
        Rows whose slot is free (or still mid-chunked-prefill) run too
        (fixed shape = one XLA program); the engine parks them at
        ``pos = Tmax`` so their write is an out-of-bounds scatter jax
        DROPS — an in-range dummy position would clobber real K/V, e.g.
        a prefix-cache copy at position 0 of a mid-prefill row.  The
        caches may carry more rows than ``S`` (scratch + prefix pool);
        rows past S are never written or attended here.  With
        ``page_table`` (S, P) int32 the caches are PAGED (parked rows'
        writes route out of bounds and drop, and unassigned table
        entries read the never-written zero page — see
        ``MultiHeadAttention.forward_step_slots``).  Inference mode
        assumed."""
        from ..ndarray import NDArray

        s = tok.shape[0]
        tok2 = tok.reshape((s, 1))
        x = self.wte(tok2) + self.wpe(NDArray(pos.reshape((s, 1))))
        new_caches = []
        for blk, cache in zip(self.blocks, caches):
            x, c = blk.forward_step_slots(x, cache, pos, page_table,
                                          paged_kernel)
            new_caches.append(c)
        x = self.ln_f(x)
        logits = F.FullyConnected(x, self.wte.weight.data(), None,
                                  num_hidden=self.vocab_size, no_bias=True,
                                  flatten=False)
        return logits.reshape((s, self.vocab_size)), new_caches

    def verify_slots(self, tokens_nd, caches, pos, page_table=None,
                     paged_kernel=False):
        """Speculative VERIFY forward (docs/serving.md "Speculative
        decode"): the decode step generalized from one token per slot to
        a (S, W) window — structurally :meth:`prefill_slots` with
        ``offset=pos`` and ``slot_idx=arange(S)``, but with logits kept
        at EVERY window position instead of only the last real one.

        Row s consumes window tokens at absolute positions
        ``[pos[s], pos[s]+W)``, writes every layer's K/V there through
        the standard slot/page scatter (parked rows at ``pos >= Tmax``
        route out of bounds and drop, exactly like :meth:`decode_step`),
        attends causally over the full cache row, and returns logits
        (S, W, vocab) — logits[s, i] is the next-token distribution
        after consuming window token i, which is what the engine's
        rejection rule samples from.  Inference only."""
        import jax.numpy as jnp

        from ..ndarray import NDArray

        s, t = tokens_nd.shape
        apos = pos[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]
        # clamp the embedding lookup only (parked rows / windows running
        # past Tmax): their K/V writes are OOB scatters (dropped) and
        # their logits are never accepted
        x = self.wte(tokens_nd) + \
            self.wpe(NDArray(jnp.minimum(apos, self.max_length - 1)))
        new_caches = []
        for blk, cache in zip(self.blocks, caches):
            # slot_idx=None = "row i IS slot i": the cache row read
            # lowers to a slice, not an identity-permutation gather
            x, c = blk.forward_prefill_slots(x, cache, None, pos,
                                             page_table, paged_kernel)
            new_caches.append(c)
        x = self.ln_f(x)
        logits = F.FullyConnected(x, self.wte.weight.data(), None,
                                  num_hidden=self.vocab_size, no_bias=True,
                                  flatten=False)
        return logits.reshape((s, t, self.vocab_size)), new_caches

    def draft_slots(self, tok, caches, pos, n_tokens, draft_layers,
                    temperature, top_k, top_p, keys, poison=None,
                    page_table=None):
        """Self-speculative DRAFTER: propose ``n_tokens`` tokens per
        slot by early-exiting through the first ``draft_layers``
        transformer blocks (then ``ln_f`` + the tied LM head) — no
        second model, and the slot caches' leading layers ARE the
        drafter's KV state.  The whole k-step loop runs inside ONE
        compiled call (``lax.fori_loop``), so a speculation cycle costs
        two dispatches (draft + verify) instead of k+1.

        STRICTLY READ-ONLY on ``caches``: speculated K/V live in
        per-layer window buffers carried through the loop
        (``forward_step_window``), so an abandoned draft — verify
        fault, rejected tokens, poisoned head — cannot leave stale or
        non-finite state anywhere shared; nothing is returned but the
        proposed tokens (S, n_tokens) int32.

        Each step samples with the SAME per-request seeded rule the
        verify forward uses (``sample_tokens`` folded at the consumed
        token's absolute position), so a drafter whose early-exit
        logits track the full model proposes exactly the token the
        verifier will sample — acceptance degrades gracefully with
        drafter quality and correctness never depends on it.
        ``poison`` (traced f32 scalar, normally 0.0) is added to the
        draft logits — the ``serving.draft_logits`` fault site's NaN
        splice rides it without recompiling."""
        import jax
        import jax.numpy as jnp

        from ..ndarray import NDArray
        from ..serving.sampling import sample_tokens

        _dense_blocks_only(self)
        if not 1 <= int(draft_layers) <= len(self.blocks):
            raise ValueError(
                f"draft_layers={draft_layers} must be in "
                f"[1, {len(self.blocks)}]")
        blocks = self.blocks[:int(draft_layers)]
        s = tok.shape[0]
        blk0 = self.blocks[0]
        h, d = blk0.attn._num_heads, blk0.attn._head_dim
        # window buffers follow the cache dtype EXCEPT under int8
        # quantization: the speculated K/V are transient registers, and
        # quantizing them would double-quantize the draft's own window
        # reads for zero memory win (the windows never touch the pool)
        dt = caches[0]["k"].dtype
        if jnp.issubdtype(dt, jnp.integer):
            dt = jnp.float32
        wins = tuple((jnp.zeros((s, n_tokens, h, d), dt),
                      jnp.zeros((s, n_tokens, h, d), dt))
                     for _ in blocks)
        tok_j = tok.jax if isinstance(tok, NDArray) else tok

        def body(i, carry):
            cur, wins, out = carry
            p = pos + i
            x = self.wte(NDArray(cur.reshape((s, 1)))) + self.wpe(
                NDArray(jnp.minimum(p, self.max_length - 1)
                        .reshape((s, 1))))
            new_wins = []
            for blk, (wk, wv), cache in zip(blocks, wins, caches):
                x, wk, wv = blk.forward_step_window(
                    x, cache, pos, wk, wv, i, page_table)
                new_wins.append((wk, wv))
            x = self.ln_f(x)
            logits = F.FullyConnected(
                x, self.wte.weight.data(), None,
                num_hidden=self.vocab_size, no_bias=True, flatten=False)
            lg = logits.reshape((s, self.vocab_size)).jax
            if poison is not None:
                lg = lg + poison
            nxt = sample_tokens(lg, temperature, top_k, top_p, keys, p)
            return nxt, tuple(new_wins), out.at[:, i].set(nxt)

        _, _, out = jax.lax.fori_loop(
            0, int(n_tokens), body,
            (tok_j.astype(jnp.int32), wins,
             jnp.zeros((s, int(n_tokens)), jnp.int32)))
        return out

    def generate(self, prompt, max_new_tokens, temperature=1.0, top_k=0,
                 seed=0):
        """Autoregressive generation with a KV cache, as ONE jitted XLA
        computation (prefill + decode via lax.fori_loop +
        dynamic_update_slice — O(T) memory, no retraces across calls with
        the same shapes).  ``temperature=0`` is greedy argmax; otherwise
        samples from the (optionally top-k-truncated) softmax.

        Capability add over the reference: MXNet-era GPT generation lived
        in GluonNLP scripts with per-step Python dispatch; here the whole
        loop lowers to XLA.
        """
        import jax
        import jax.numpy as jnp

        from .. import base as _base
        from ..ndarray import NDArray
        from ..ndarray import array as nd_array

        _dense_blocks_only(self)
        if isinstance(prompt, NDArray):
            prompt_j = prompt.jax.astype(jnp.int32)
        else:
            import numpy as onp
            prompt_j = jnp.asarray(onp.asarray(prompt), jnp.int32)
        b, tp = prompt_j.shape
        total = tp + int(max_new_tokens)
        if total > self.max_length:
            raise ValueError(f"prompt+new = {total} exceeds max_length="
                             f"{self.max_length}")

        from ..gluon.cached_op import collect_block_params
        items = collect_block_params(self)
        param_vals = tuple(p._data.jax for p in items)
        net = self

        # params may live sharded on a mesh (post-ShardedTrainer): an
        # op-derived (committed) prompt on a different device set raises
        # 'incompatible devices' — replicate it onto the params' mesh
        wsh = getattr(param_vals[0], "sharding", None) if param_vals else None
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as _P
        if isinstance(wsh, NamedSharding):
            prompt_j = jax.device_put(prompt_j,
                                      NamedSharding(wsh.mesh, _P()))

        # cache the jitted program per decode SHAPE — jax.jit caches by
        # function object, so a fresh closure per call would recompile
        # every generate().  temperature is a traced scalar argument (a
        # temperature schedule must not recompile); only the
        # greedy/sampling structure and top_k change the program.
        greedy = temperature <= 0
        top_k = min(int(top_k), self.vocab_size) \
            if top_k and top_k > 0 else 0
        cfg = (b, tp, int(max_new_tokens), greedy, top_k)
        jit_cache = self.__dict__.setdefault("_generate_jit_cache", {})
        run = jit_cache.get(cfg)
        if run is None:
            from ..ndarray.ndarray import swap_values

            @jax.jit
            def run(param_vals, prompt_j, key, temp):
                # re-capture the LIVE payload objects at trace time: if
                # reset_ctx/astype replaced Parameter._data since the last
                # trace, swapping into the stale objects would bake the
                # then-current weights in as constants
                live_nds = [p._data for p in items]
                with swap_values(live_nds, param_vals):
                    with _base.training_mode(False):
                        rec = _base.set_recording(False)
                        try:
                            def pick(logits_j, key, t):
                                if greedy:
                                    return jnp.argmax(
                                        logits_j, axis=-1).astype(jnp.int32)
                                lg = logits_j / jnp.maximum(temp, 1e-6)
                                if top_k:
                                    kth = jnp.sort(lg, axis=-1)[:, -top_k]
                                    lg = jnp.where(lg < kth[:, None],
                                                   -1e30, lg)
                                return jax.random.categorical(
                                    jax.random.fold_in(key, t), lg,
                                    axis=-1).astype(jnp.int32)

                            caches = net.init_cache(b, total)
                            # batched prefill: one causal pass fills all
                            # layer caches for positions [0, tp)
                            logits0, caches = net.prefill(
                                NDArray(prompt_j), caches)
                            first = pick(logits0.jax, key, tp - 1)
                            tokens = jnp.concatenate(
                                [prompt_j, first[:, None],
                                 jnp.zeros((b, total - tp - 1), jnp.int32)],
                                axis=1) if total > tp else prompt_j

                            def body(t, carry):
                                tokens, caches, key = carry
                                tok_t = jax.lax.dynamic_slice(
                                    tokens, (0, t), (b, 1))
                                logits, caches = net.forward_step(
                                    NDArray(tok_t), caches, t)
                                nxt = pick(logits.jax, key, t)
                                tokens = jax.lax.dynamic_update_slice(
                                    tokens, nxt[:, None], (0, t + 1))
                                return tokens, caches, key

                            tokens, _, _ = jax.lax.fori_loop(
                                tp, total - 1, body, (tokens, caches, key))
                            return tokens
                        finally:
                            _base.set_recording(rec)

            jit_cache[cfg] = run
        out = run(param_vals, prompt_j, jax.random.PRNGKey(seed),
                  jnp.asarray(max(float(temperature), 0.0), jnp.float32))
        return nd_array(out, dtype="int32")



class GPT2Model(_GPT2Decoding, HybridBlock):
    """Decoder-only LM: tokens (B, T) int32 → logits (B, T, vocab)."""

    def __init__(self, vocab_size=50257, units=768, num_layers=12,
                 num_heads=12, max_length=1024, dropout=0.1,
                 layer_norm_eps=1e-5, num_experts=0, moe_every=2,
                 moe_top_k=2, moe_capacity_factor=1.25, scan_layers=None,
                 remat=False, **kwargs):
        super().__init__(**kwargs)
        self._scan_layers = scan_layers
        self._remat = remat
        self._units = units
        self.vocab_size = vocab_size
        self.max_length = max_length
        self.wte = Embedding(vocab_size, units)
        annotate(self.wte.weight, "vocab", "embed")
        self.wpe = Embedding(max_length, units)
        annotate(self.wpe.weight, "seq", "embed")
        self.drop = Dropout(dropout) if dropout else None
        self.blocks = []
        for i in range(num_layers):
            if num_experts and i % moe_every == moe_every - 1:
                from .moe import MoETransformerBlock
                blk = MoETransformerBlock(
                    units, 4 * units, num_heads, num_experts,
                    top_k=moe_top_k, capacity_factor=moe_capacity_factor,
                    dropout=dropout, causal=True,
                    layer_norm_eps=layer_norm_eps)
            else:
                blk = TransformerBlock(units, 4 * units, num_heads,
                                       dropout=dropout, causal=True,
                                       layer_norm_eps=layer_norm_eps)
            self.register_child(blk, f"h{i}")
            self.blocks.append(blk)
        self.ln_f = LayerNorm(epsilon=layer_norm_eps, in_channels=units)

    def forward(self, tokens):
        b, t = tokens.shape
        if isinstance(t, int) and t > self.max_length:
            raise ValueError(
                f"sequence length {t} exceeds max_length={self.max_length} "
                "(position table size)")
        pos = F.arange_like(tokens, axis=1).astype("int32")
        x = self.wte(tokens) + self.wpe(pos)
        x = _par.with_sharding_constraint(x, "batch", "seq", None)
        if self.drop is not None:
            x = self.drop(x)
        from .transformer import run_blocks
        x = run_blocks(self.blocks, x, scan=self._scan_layers,
                       remat=self._remat)
        x = self.ln_f(x)
        # tied lm head: logits = x · wteᵀ (vocab-parallel over tp)
        logits = F.FullyConnected(x, self.wte.weight.data(), None,
                                  num_hidden=self.vocab_size, no_bias=True,
                                  flatten=False)
        return _par.with_sharding_constraint(logits, "batch", "seq", "vocab")


def gpt2_lm_loss(logits, labels, aux_weight=0.01):
    """Next-token cross entropy; labels (B, T) already shifted.  Any MoE
    router aux losses recorded during the forward are drained and added
    (weight 0 cost for dense models — the collector is simply empty)."""
    from .moe import pop_aux_losses
    # nll = logsumexp(logits) - logits[label]: skips materializing the full
    # (B, T, V) log_softmax in f32 — the logsumexp reduction reads logits
    # once and the gather is O(B*T) (HBM matters: V=50k dominates activations)
    lse = F.logsumexp(logits, axis=-1)
    picked = F.pick(logits, labels, axis=-1)
    nll = lse - picked
    loss = nll.mean()
    for aux in pop_aux_losses():
        loss = loss + aux * aux_weight
    return loss


def get_gpt2(name="gpt2_124m", **kwargs):
    layers, units, heads = _CONFIGS[name]
    cfg = dict(units=units, num_layers=layers, num_heads=heads)
    cfg.update(kwargs)
    return GPT2Model(**cfg)
