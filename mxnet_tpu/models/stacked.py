"""Stacked-layer GPT-2 for pipeline parallelism and O(1)-depth compiles.

TPU-first trunk representation (no reference analogue — MXNet has no PP,
SURVEY.md §2.4): all transformer layers live as ONE set of parameters
with a leading ``layers`` dim.  Single-stage execution is a
``lax.scan`` over layers (compile time independent of depth, with
``jax.checkpoint`` rematerialization per layer); under a mesh with
``pp > 1`` the stack splits into contiguous stages and runs the GPipe
schedule from :mod:`mxnet_tpu.parallel.pipeline` (microbatches ride the
ICI ring between stages).  Composes with dp (batch) sharding; tensor/
sequence parallelism use the per-layer (non-stacked) GPT2Model, whose
GSPMD path shards heads/sequence.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import parallel as _par
from ..gluon.block import HybridBlock
from ..gluon.nn import Embedding
from ..ndarray.ops import invoke
from ..parallel.sharding import annotate

__all__ = ["StackedGPT2Model", "get_stacked_gpt2"]


def _ln(x, g, b, eps):
    m = jnp.mean(x, axis=-1, keepdims=True)
    v = jnp.var(x, axis=-1, keepdims=True)
    return (x - m) * jax.lax.rsqrt(v + eps) * g + b


class StackedGPT2Model(HybridBlock):
    """Decoder-only LM with a scanned/pipelined trunk.

    tokens (B, T) int32 → logits (B, T, vocab).  Weights are stacked
    (num_layers, ...) and annotated with the "layers" logical axis
    ("layers" → pp in the default sharding rules).
    """

    def __init__(self, vocab_size=50257, units=768, num_layers=12,
                 num_heads=12, max_length=1024, layer_norm_eps=1e-5,
                 num_microbatches=None, remat=True, dtype="float32",
                 **kwargs):
        super().__init__(**kwargs)
        if units % num_heads:
            raise ValueError("units % num_heads != 0")
        self.vocab_size = vocab_size
        self.max_length = max_length
        self._units = units
        self._num_layers = num_layers
        self._num_heads = num_heads
        self._eps = layer_norm_eps
        self._num_microbatches = num_microbatches
        self._remat = remat
        self.wte = Embedding(vocab_size, units, dtype=dtype)
        annotate(self.wte.weight, "vocab", "embed")
        self.wpe = Embedding(max_length, units, dtype=dtype)
        annotate(self.wpe.weight, "seq", "embed")

        l, d, h4 = num_layers, units, 4 * units
        g = self.params.get

        def p(name, shape, init):
            prm = g(name, shape=shape, dtype=dtype, init=init,
                    allow_deferred_init=True)
            annotate(prm, *( ("layers",) + (None,) * (len(shape) - 1) ))
            return prm

        self.ln1_g = p("ln1_gamma", (l, d), "ones")
        self.ln1_b = p("ln1_beta", (l, d), "zeros")
        self.wqkv = p("wqkv", (l, d, 3 * d), "xavier")
        self.bqkv = p("bqkv", (l, 3 * d), "zeros")
        self.wo = p("wo", (l, d, d), "xavier")
        self.bo = p("bo", (l, d), "zeros")
        self.ln2_g = p("ln2_gamma", (l, d), "ones")
        self.ln2_b = p("ln2_beta", (l, d), "zeros")
        self.w1 = p("w1", (l, d, h4), "xavier")
        self.b1 = p("b1", (l, h4), "zeros")
        self.w2 = p("w2", (l, h4, d), "xavier")
        self.b2 = p("b2", (l, d), "zeros")
        self.lnf_g = g("lnf_gamma", shape=(d,), dtype=dtype, init="ones")
        self.lnf_b = g("lnf_beta", shape=(d,), dtype=dtype, init="zeros")
        self._stacked = [self.ln1_g, self.ln1_b, self.wqkv, self.bqkv,
                         self.wo, self.bo, self.ln2_g, self.ln2_b,
                         self.w1, self.b1, self.w2, self.b2]

    # ------------------------------------------------------------------
    def _layer(self, p, x):
        from ..ops.attention import flash_attention
        (l1g, l1b, wqkv, bqkv, wo, bo, l2g, l2b, w1, b1, w2, b2) = p
        bsz, t, d = x.shape
        h = self._num_heads
        hn = _ln(x, l1g, l1b, self._eps)
        qkv = jnp.einsum("btd,de->bte", hn, wqkv) + bqkv
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(bsz, t, h, d // h)
        k = k.reshape(bsz, t, h, d // h)
        v = v.reshape(bsz, t, h, d // h)
        a = flash_attention(q, k, v, causal=True).reshape(bsz, t, d)
        x = x + jnp.einsum("btd,de->bte", a, wo) + bo
        hn = _ln(x, l2g, l2b, self._eps)
        ff = jax.nn.gelu(jnp.einsum("btd,dh->bth", hn, w1) + b1)
        x = x + jnp.einsum("bth,hd->btd", ff, w2) + b2
        return _par.with_sharding_constraint(x, "batch", None, None)

    def forward(self, tokens):
        from ..ndarray import ops as F
        mesh = _par.current_mesh()
        pp = _par.axis_size(mesh, "pp") if mesh is not None else 1
        layer = self._layer
        if self._remat:
            layer = jax.checkpoint(layer)
        nl = self._num_layers
        if nl % max(pp, 1):
            raise ValueError(f"{nl} layers not divisible by pp={pp}")

        pos = F.arange_like(tokens, axis=1).astype("int32")
        x_nd = self.wte(tokens) + self.wpe(pos)

        def trunk(xv, *leaves):
            def scan_layers(stack, xx):
                def body(carry, sl):
                    return layer(sl, carry), None
                out, _ = jax.lax.scan(body, xx, stack)
                return out

            if pp > 1:
                from ..parallel.pipeline import gpipe
                stages = tuple(
                    lv.reshape(pp, nl // pp, *lv.shape[1:])
                    for lv in leaves)
                local_b = xv.shape[0] // max(_par.axis_size(mesh, "dp"), 1)
                if self._num_microbatches is not None:
                    # explicit request is honored verbatim — gpipe raises
                    # if it doesn't divide the per-dp-shard batch
                    m = self._num_microbatches
                else:
                    m = max(2 * pp, 2)
                    while local_b % m:  # largest feasible default
                        m -= 1
                return gpipe(scan_layers, stages, xv,
                             num_microbatches=m, mesh=mesh)
            return scan_layers(tuple(leaves), xv)

        x_nd = invoke("stacked_gpt2_trunk", trunk,
                      [x_nd] + [s.data() for s in self._stacked])
        x_nd = invoke(
            "final_ln",
            lambda xv, gv, bv: _ln(xv, gv, bv, self._eps),
            [x_nd, self.lnf_g.data(), self.lnf_b.data()])
        logits = F.FullyConnected(x_nd, self.wte.weight.data(), None,
                                  num_hidden=self.vocab_size, no_bias=True,
                                  flatten=False)
        return _par.with_sharding_constraint(logits, "batch", None, "vocab")


def get_stacked_gpt2(name="gpt2_124m", **kwargs):
    from .gpt2 import _CONFIGS
    layers, units, heads = _CONFIGS[name]
    cfg = dict(units=units, num_layers=layers, num_heads=heads)
    cfg.update(kwargs)
    return StackedGPT2Model(**cfg)
