"""Mixture-of-Experts layers with expert parallelism over the ``ep`` mesh
axis.

Capability add over the reference (SURVEY.md §2.4: "EP/MoE: none" in
MXNet).  TPU-first design: experts live as stacked (E, ...) parameters
annotated with the "expert" logical axis (sharded over ``ep`` by the
default rules), and routing is the dense GShard/Switch dispatch — one-hot
dispatch/combine einsums with a fixed per-expert capacity so every shape
is static and every FLOP lands on the MXU.  XLA turns the expert einsums
into per-shard grouped matmuls with an all-to-all across ``ep``.

Router aux losses (load-balancing) are recorded into an ambient collector
during forward; loss functions drain it via :func:`pop_aux_losses`.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .. import parallel as _par
from ..gluon.block import HybridBlock
from ..ndarray.ops import invoke
from ..parallel.sharding import annotate

__all__ = ["MoELayer", "MoETransformerBlock", "pop_aux_losses",
           "aux_loss_scope"]

from .. import base as _base

_WARNED_CACHED = False


def pop_aux_losses():
    """Drain and return the aux losses recorded since the last pop
    (scalar NDArrays; empty list if no MoE layer ran)."""
    return _base.pop_aux_losses()


class aux_loss_scope:
    """Context manager guaranteeing a clean aux-loss slate (used by
    training loops that may abandon traces)."""

    def __enter__(self):
        _base.pop_aux_losses()
        return self

    def __exit__(self, *a):
        _base.pop_aux_losses()


def _moe_ffn(x, wg, w1, b1, w2, b2, *, num_experts, top_k, capacity,
             activation):
    """Pure-jax GShard dispatch; x (B, T, D) → (y (B, T, D), aux scalar)."""
    b, t, d = x.shape
    e, c = num_experts, capacity
    n = b * t
    xf = x.reshape(n, d)
    logits = jnp.einsum("nd,ed->ne", xf.astype(jnp.float32),
                        wg.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)              # (N, E)
    gate_vals, gate_idx = jax.lax.top_k(probs, top_k)    # (N, k)
    gate_vals = gate_vals / jnp.sum(gate_vals, axis=-1, keepdims=True)

    combine = jnp.zeros((n, e, c), jnp.float32)
    counts = jnp.zeros((e,), jnp.float32)
    for j in range(top_k):
        m = jax.nn.one_hot(gate_idx[:, j], e, dtype=jnp.float32)  # (N, E)
        pos = jnp.cumsum(m, axis=0) - 1.0 + counts[None, :]
        keep = (pos < c) * m                              # (N, E)
        slot = jax.nn.one_hot(pos.astype(jnp.int32), c,
                              dtype=jnp.float32)          # (N, E, C)
        combine = combine + gate_vals[:, j, None, None] * \
            keep[:, :, None] * slot
        counts = counts + jnp.sum(m, axis=0)
    dispatch = (combine > 0).astype(xf.dtype)             # (N, E, C)

    x_e = jnp.einsum("nec,nd->ecd", dispatch, xf)
    x_e = _par.with_sharding_constraint(x_e, "expert", None, None)
    h = jnp.einsum("ecd,edh->ech", x_e, w1,
                   preferred_element_type=jnp.float32) + b1[:, None, :]
    h = activation(h).astype(xf.dtype)
    h = _par.with_sharding_constraint(h, "expert", None, "mlp")
    y_e = jnp.einsum("ech,ehd->ecd", h, w2,
                     preferred_element_type=jnp.float32) + b2[:, None, :]
    y_e = _par.with_sharding_constraint(y_e, "expert", None, None)
    y = jnp.einsum("nec,ecd->nd", combine, y_e.astype(jnp.float32))

    # GShard load-balance loss: E * Σ_e (token fraction)·(mean router prob)
    top1 = jax.nn.one_hot(gate_idx[:, 0], e, dtype=jnp.float32)
    frac = jnp.mean(top1, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(frac * mean_prob)
    return y.reshape(b, t, d).astype(x.dtype), aux


class MoELayer(HybridBlock):
    """Top-k routed expert FFN (drop-in for PositionwiseFFN).

    Parameters are stacked over the expert dim and annotated "expert" so
    the default sharding rules place them over the ``ep`` mesh axis.
    """

    def __init__(self, units, hidden_size, num_experts, top_k=2,
                 capacity_factor=1.25, activation="gelu", dropout=0.0,
                 dtype="float32", **kwargs):
        super().__init__(**kwargs)
        from ..gluon.nn import Dropout
        self.dropout = Dropout(dropout) if dropout else None
        self._units = units
        self._hidden = hidden_size
        self._num_experts = num_experts
        self._top_k = min(top_k, num_experts)
        self._capacity_factor = capacity_factor
        self._act_name = activation
        self.gate = self.params.get(
            "gate", shape=(num_experts, units), dtype=dtype,
            init="xavier", allow_deferred_init=True)
        annotate(self.gate, None, "embed")
        self.w1 = self.params.get(
            "w1", shape=(num_experts, units, hidden_size), dtype=dtype,
            init="xavier", allow_deferred_init=True)
        annotate(self.w1, "expert", "embed", "mlp")
        self.b1 = self.params.get(
            "b1", shape=(num_experts, hidden_size), dtype=dtype,
            init="zeros", allow_deferred_init=True)
        annotate(self.b1, "expert", "mlp")
        self.w2 = self.params.get(
            "w2", shape=(num_experts, hidden_size, units), dtype=dtype,
            init="xavier", allow_deferred_init=True)
        annotate(self.w2, "expert", "mlp", "embed")
        self.b2 = self.params.get(
            "b2", shape=(num_experts, units), dtype=dtype,
            init="zeros", allow_deferred_init=True)
        annotate(self.b2, "expert", "embed")

    def capacity(self, n_tokens: int) -> int:
        cap = int(math.ceil(self._top_k * n_tokens / self._num_experts
                            * self._capacity_factor))
        return max(cap, self._top_k)

    def forward(self, x):
        b, t = x.shape[0], x.shape[1]
        act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
               "silu": jax.nn.silu}[self._act_name]

        def f(xv, wg, w1, b1, w2, b2):
            return _moe_ffn(
                xv, wg, w1, b1, w2, b2, num_experts=self._num_experts,
                top_k=self._top_k, capacity=self.capacity(b * t),
                activation=act)

        y, aux = invoke("moe_ffn", f,
                        [x, self.gate.data(), self.w1.data(),
                         self.b1.data(), self.w2.data(), self.b2.data()])
        # record only when a loss will drain it within the same tape/trace:
        # eager autograd recording, or a trace whose owner opened an
        # aux-collection scope (ShardedTrainer, CachedOp — the latter
        # functionalizes the losses as extra traced outputs).  Tracers
        # outside such a scope must NOT be recorded — they would leak out
        # of their trace.
        traced = isinstance(aux.jax, jax.core.Tracer)
        if traced and _base.aux_collection_active():
            _base.record_aux_loss(aux)
        elif not traced and _base.is_recording():
            _base.record_aux_loss(aux)   # NDArray, autograd node intact
        elif traced:
            global _WARNED_CACHED
            if not _WARNED_CACHED:
                import logging
                logging.warning(
                    "MoE router aux loss is dropped inside a foreign "
                    "trace with no aux-collection scope; run the layer "
                    "imperatively, via hybridize()/CachedOp, or under "
                    "parallel.ShardedTrainer to include it")
                _WARNED_CACHED = True
        if self.dropout is not None:
            y = self.dropout(y)
        return y


class MoETransformerBlock(HybridBlock):
    """Pre-LN transformer layer whose FFN is a routed MoE."""

    def __init__(self, units, hidden_size, num_heads, num_experts,
                 top_k=2, capacity_factor=1.25, dropout=0.0,
                 attention_dropout=0.0, causal=True, layer_norm_eps=1e-5,
                 **kwargs):
        super().__init__(**kwargs)
        from ..gluon.nn import LayerNorm
        from .transformer import MultiHeadAttention
        self.ln1 = LayerNorm(epsilon=layer_norm_eps, in_channels=units)
        self.attn = MultiHeadAttention(
            units, num_heads, dropout=dropout,
            attention_dropout=attention_dropout, causal=causal)
        self.ln2 = LayerNorm(epsilon=layer_norm_eps, in_channels=units)
        self.moe = MoELayer(units, hidden_size, num_experts, top_k=top_k,
                            capacity_factor=capacity_factor,
                            dropout=dropout)

    def forward(self, x, mask=None):
        x = x + self.attn(self.ln1(x), mask)
        x = _par.with_sharding_constraint(x, "batch", "seq", None)
        x = x + self.moe(self.ln2(x))
        return _par.with_sharding_constraint(x, "batch", "seq", None)
