"""Sockeye-style Transformer NMT (BASELINE config 4: Transformer-big
WMT En-De).

Parity: the reference kept NMT out of tree (Sockeye over BucketingModule +
the contrib fused attention matmuls, SURVEY.md §2.4 "bucketing"); here the
encoder-decoder transformer is in-tree on the same TP/SP-aware blocks as
BERT/GPT-2, with sinusoidal positions, tied target embeddings and
label-smoothed CE — trainable via ShardedTrainer (one jitted SPMD step) or
Module/BucketingModule (variable-length buckets share parameters, the
compile-cache discipline of SURVEY.md §7.3 hard part 3).
"""
from __future__ import annotations

import math

from ..gluon.block import HybridBlock
from ..gluon.nn import Dense, Dropout, Embedding, LayerNorm
from ..ndarray import ops as F
from ..parallel.sharding import annotate
from .transformer import (MultiHeadAttention, PositionwiseFFN,
                          TransformerEncoderLayer, run_blocks)

_CONFIGS = {
    # name: (layers, units, hidden, heads)
    "transformer_base": (6, 512, 2048, 8),
    "transformer_big": (6, 1024, 4096, 16),
}


def _sinusoidal_positions(x, units):
    """(B, T, U) positional encoding added functionally (Sockeye default —
    no learned position table, any length up to the trace shape works)."""
    import jax.numpy as jnp

    from ..ndarray.ops import _as_nd, invoke

    def f(v):
        t = v.shape[1]
        pos = jnp.arange(t, dtype=jnp.float32)[:, None]
        dim = jnp.arange(units // 2, dtype=jnp.float32)[None, :]
        ang = pos / jnp.power(10000.0, 2.0 * dim / units)
        pe = jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=-1)
        return (v + pe[None].astype(v.dtype))

    return invoke("sinusoidal_pos", f, [_as_nd(x)])


class TransformerDecoderBlock(HybridBlock):
    """Pre-LN decoder layer: causal self-attention → encoder cross-attention
    → FFN."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 attention_dropout=0.0, layer_norm_eps=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.ln1 = LayerNorm(epsilon=layer_norm_eps, in_channels=units)
        self.self_attn = MultiHeadAttention(
            units, num_heads, dropout=dropout,
            attention_dropout=attention_dropout, causal=True)
        self.ln2 = LayerNorm(epsilon=layer_norm_eps, in_channels=units)
        self.cross_attn = MultiHeadAttention(
            units, num_heads, dropout=dropout,
            attention_dropout=attention_dropout, causal=False)
        self.ln3 = LayerNorm(epsilon=layer_norm_eps, in_channels=units)
        self.ffn = PositionwiseFFN(units, hidden_size, dropout=dropout)

    def forward(self, x, memory, mem_mask=None):
        x = x + self.self_attn(self.ln1(x))
        x = x + self.cross_attn(self.ln2(x), mem_mask, memory)
        return x + self.ffn(self.ln3(x))


class TransformerNMT(HybridBlock):
    """Encoder-decoder transformer: (src, tgt) int32 token batches →
    logits (B, T_tgt, tgt_vocab).  ``tgt`` is the shifted-right target
    (BOS-prefixed); labels are the unshifted target."""

    def __init__(self, src_vocab_size, tgt_vocab_size=None, units=512,
                 hidden_size=2048, num_layers=6, num_heads=8,
                 dropout=0.1, layer_norm_eps=1e-5, shared_embed=False,
                 scan_layers=None, remat=False, **kwargs):
        super().__init__(**kwargs)
        tgt_vocab_size = tgt_vocab_size or src_vocab_size
        self._units = units
        self.src_vocab_size = src_vocab_size
        self.tgt_vocab_size = tgt_vocab_size
        self._scan_layers = scan_layers
        self._remat = remat
        self.src_embed = Embedding(src_vocab_size, units)
        annotate(self.src_embed.weight, "vocab", "embed")
        if shared_embed:
            if tgt_vocab_size != src_vocab_size:
                raise ValueError("shared_embed needs equal vocab sizes")
            self.tgt_embed = self.src_embed
        else:
            self.tgt_embed = Embedding(tgt_vocab_size, units)
            annotate(self.tgt_embed.weight, "vocab", "embed")
        self.drop = Dropout(dropout) if dropout else None
        self.enc_layers = []
        for i in range(num_layers):
            layer = TransformerEncoderLayer(
                units, hidden_size, num_heads, dropout=dropout,
                layer_norm_eps=layer_norm_eps)
            self.register_child(layer, f"enc{i}")
            self.enc_layers.append(layer)
        self.enc_ln = LayerNorm(epsilon=layer_norm_eps, in_channels=units)
        self.dec_layers = []
        for i in range(num_layers):
            layer = TransformerDecoderBlock(
                units, hidden_size, num_heads, dropout=dropout,
                layer_norm_eps=layer_norm_eps)
            self.register_child(layer, f"dec{i}")
            self.dec_layers.append(layer)
        self.dec_ln = LayerNorm(epsilon=layer_norm_eps, in_channels=units)

    # ------------------------------------------------------------------
    def _src_mask(self, src, src_valid_length):
        if src_valid_length is None:
            return None
        b, ts = src.shape
        steps = F.arange_like(src, axis=1)
        return (steps.reshape((1, 1, 1, ts)) <
                src_valid_length.reshape((b, 1, 1, 1)))

    def encode(self, src, src_valid_length=None):
        x = self.src_embed(src) * math.sqrt(self._units)
        x = _sinusoidal_positions(x, self._units)
        if self.drop is not None:
            x = self.drop(x)
        mask = self._src_mask(src, src_valid_length)
        x = run_blocks(self.enc_layers, x, mask, scan=self._scan_layers,
                       remat=self._remat)
        return self.enc_ln(x)

    def decode(self, tgt, memory, src=None, src_valid_length=None):
        y = self.tgt_embed(tgt) * math.sqrt(self._units)
        y = _sinusoidal_positions(y, self._units)
        if self.drop is not None:
            y = self.drop(y)
        mem_mask = (self._src_mask(src, src_valid_length)
                    if src is not None else None)
        import jax

        from ..ndarray import NDArray
        if self._remat and isinstance(y.jax, jax.core.Tracer):
            # activation checkpointing for the decoder stack too (the
            # loop-path remat of transformer.run_blocks: per-layer
            # jax.checkpoint with the layer index folded into the trace
            # key so fwd and rematerialized traces draw identical
            # dropout masks); memory is an explicit input so it is
            # saved, not recomputed
            from .. import random as _random
            providers = _random._trace_providers()
            base_key = providers[-1].key if providers else None
            for i, blk in enumerate(self.dec_layers):
                def f(h, mem, _blk=blk, _i=i):
                    if base_key is not None:
                        _random.push_trace_key(
                            jax.random.fold_in(base_key, 1 << 20 | _i))
                    try:
                        return _blk(NDArray(h), NDArray(mem),
                                    mem_mask).jax
                    finally:
                        if base_key is not None:
                            _random.pop_trace_key()
                y = NDArray(jax.checkpoint(f)(y.jax, memory.jax))
        else:
            for blk in self.dec_layers:
                y = blk(y, memory, mem_mask)
        y = self.dec_ln(y)
        # tied output projection: logits = y · tgt_embedᵀ
        return F.FullyConnected(y, self.tgt_embed.weight.data(), None,
                                num_hidden=self.tgt_vocab_size,
                                no_bias=True, flatten=False)

    def forward(self, src, tgt, src_valid_length=None):
        memory = self.encode(src, src_valid_length)
        return self.decode(tgt, memory, src, src_valid_length)

    # ----------------------------------------------------------- inference
    def translate(self, src, src_valid_length=None, max_length=32,
                  bos_id=1, eos_id=2, beam_size=1, alpha=1.0):
        """Greedy (``beam_size=1``) or length-normalized beam decode
        (Sockeye's default inference; ``alpha`` is the length-penalty
        exponent).  Returns (B, <=max_length) int32 tokens padded with
        EOS."""
        if beam_size > 1:
            return self._beam_translate(src, src_valid_length, max_length,
                                        bos_id, eos_id, beam_size, alpha)
        import numpy as onp

        from .. import base as _base
        from ..ndarray import NDArray
        from ..ndarray import array as nd_array

        _put = self._mesh_put()
        src = _put(src)
        if src_valid_length is not None:
            src_valid_length = _put(src_valid_length)

        with _base.training_mode(False):
            memory = self.encode(src, src_valid_length)
            b = src.shape[0]
            tokens = onp.full((b, 1), bos_id, dtype="int32")
            done = onp.zeros((b,), dtype=bool)
            for _ in range(max_length):
                logits = self.decode(_put(nd_array(tokens, dtype="int32")),
                                     memory, src, src_valid_length)
                nxt = logits.asnumpy()[:, -1].argmax(-1).astype("int32")
                nxt = onp.where(done, eos_id, nxt)
                done |= nxt == eos_id
                tokens = onp.concatenate([tokens, nxt[:, None]], axis=1)
                if done.all():
                    break
            return tokens[:, 1:]

    def _mesh_put(self):
        """Params may live sharded on a mesh (post-ShardedTrainer);
        returns a fn replicating eager inputs onto the same device set."""
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as _P

        from ..ndarray import NDArray

        wsh = getattr(self.src_embed.weight._data.jax, "sharding", None)
        if isinstance(wsh, NamedSharding):
            def _put(a):
                return NDArray(jax.device_put(
                    a.jax, NamedSharding(wsh.mesh, _P())))
            return _put
        return lambda a: a

    def _beam_translate(self, src, src_valid_length, max_length, bos_id,
                        eos_id, k, alpha):
        import numpy as onp

        from .. import base as _base
        from ..ndarray import array as nd_array

        from ..ndarray import ops as _ops

        _put = self._mesh_put()
        src_np = src.asnumpy() if hasattr(src, "asnumpy") else onp.asarray(src)
        b, _ = src_np.shape
        # encode each source ONCE; beams share repeated memory rows
        # (src_rep is only consulted for the padding mask — no encoder run)
        src_rep = onp.repeat(src_np, k, axis=0).astype("int32")
        vlen = None
        vlen_rep = None
        if src_valid_length is not None:
            vl_np = (src_valid_length.asnumpy()
                     if hasattr(src_valid_length, "asnumpy")
                     else onp.asarray(src_valid_length))
            vlen = _put(nd_array(vl_np.astype("int32"), dtype="int32"))
            vlen_rep = _put(nd_array(
                onp.repeat(vl_np, k, axis=0).astype("int32"), dtype="int32"))
        src_rep_nd = _put(nd_array(src_rep, dtype="int32"))

        # finished-hypothesis pool: a completed beam is recorded here the
        # step it ends, so later continuations of higher-scoring live
        # beams can never evict it before length normalization sees it
        best_norm = onp.full((b,), -onp.inf, dtype="float64")
        best_tokens = [None] * b

        def _offer(row, toks, score):
            n = score / (max(len(toks) - 1, 1) ** alpha)
            if n > best_norm[row]:
                best_norm[row] = n
                best_tokens[row] = toks.copy()

        with _base.training_mode(False):
            memory = _ops.repeat(
                self.encode(_put(nd_array(src_np.astype("int32"),
                                          dtype="int32")), vlen),
                repeats=k, axis=0)
            tokens = onp.full((b * k, 1), bos_id, dtype="int32")
            scores = onp.full((b, k), -1e30, dtype="float64")
            scores[:, 0] = 0.0           # all beams start identical: keep 1
            done = onp.zeros((b * k,), dtype=bool)
            for _ in range(max_length):
                logits = self.decode(_put(nd_array(tokens, dtype="int32")),
                                     memory, src_rep_nd, vlen_rep)
                step = logits.asnumpy()[:, -1].astype("float64")  # (b*k, V)
                logp = step - onp.log(onp.exp(
                    step - step.max(-1, keepdims=True)).sum(-1,
                                                            keepdims=True)) \
                    - step.max(-1, keepdims=True)
                vocab = logp.shape[-1]
                # finished beams only extend with EOS at zero cost
                logp[done] = -1e30
                logp[done, eos_id] = 0.0
                cand = scores.reshape(b * k, 1) + logp       # (b*k, V)
                cand = cand.reshape(b, k * vocab)
                top = onp.argpartition(-cand, k - 1, axis=1)[:, :k]
                top_scores = onp.take_along_axis(cand, top, axis=1)
                order = onp.argsort(-top_scores, axis=1)
                top = onp.take_along_axis(top, order, axis=1)
                scores = onp.take_along_axis(top_scores, order, axis=1)
                beam_idx = top // vocab                      # (b, k)
                tok_idx = (top % vocab).astype("int32")
                flat = (onp.arange(b)[:, None] * k + beam_idx).reshape(-1)
                was_done = done[flat]
                tokens = onp.concatenate(
                    [tokens[flat], tok_idx.reshape(-1, 1)], axis=1)
                done = was_done | (tokens[:, -1] == eos_id)
                newly = done & ~was_done
                for i in onp.nonzero(newly)[0]:
                    _offer(i // k, tokens[i], scores.reshape(-1)[i])
                if done.all():
                    break
            # unfinished rows fall back to the best live beam,
            # length-normalized (Sockeye lp: len^alpha)
            lengths = (tokens[:, 1:] != eos_id).sum(1) + 1.0
            norm = scores.reshape(-1) / (lengths ** alpha)
            live_best = norm.reshape(b, k).argmax(1)
            out = onp.full((b, tokens.shape[1] - 1), eos_id, dtype="int32")
            for row in range(b):
                if best_tokens[row] is None:
                    hyp = tokens.reshape(b, k, -1)[row, live_best[row], 1:]
                else:
                    hyp = best_tokens[row][1:]
                out[row, :len(hyp)] = hyp
            return out


def nmt_loss(logits, labels, valid_length=None, label_smoothing=0.1):
    """Label-smoothed cross entropy over non-pad positions (Sockeye's
    default training loss, ls=0.1)."""
    v = logits.shape[-1]
    lse = F.logsumexp(logits, axis=-1)
    picked = F.pick(logits, labels, axis=-1)
    # smoothed nll = (1-eps)*nll_target + eps * mean_nll_all
    # mean over classes of (lse - logit) = lse - mean(logits)
    nll_tgt = lse - picked
    nll_all = lse - logits.mean(axis=-1)
    nll = (1.0 - label_smoothing) * nll_tgt + label_smoothing * nll_all
    if valid_length is not None:
        b, t = labels.shape
        steps = F.arange_like(labels, axis=1)
        m = (steps.reshape((1, t)) <
             valid_length.reshape((b, 1))).astype("float32")
        return (nll * m).sum() / m.sum()
    return nll.mean()


def get_nmt(name="transformer_base", **kwargs):
    layers, units, hidden, heads = _CONFIGS[name]
    cfg = dict(units=units, hidden_size=hidden, num_layers=layers,
               num_heads=heads)
    cfg.update(kwargs)
    return TransformerNMT(**cfg)
