"""mxnet_tpu.models — in-tree model families.

Parity: python/mxnet/gluon/model_zoo (vision) plus the GluonNLP-era
transformer models the BASELINE configs require (BERT, GPT-2, Sockeye-style
transformer) — all built on TP/SP-aware blocks (see models.transformer).
"""
from . import vision
from .bert import BERTForPretrain, BERTModel, get_bert
from .gpt2 import GPT2Model, get_gpt2, gpt2_lm_loss
from .moe import MoELayer, MoETransformerBlock, pop_aux_losses
from .nmt import TransformerDecoderBlock, TransformerNMT, get_nmt, nmt_loss
from .stacked import StackedGPT2Model, get_stacked_gpt2
from .transformer import (MultiHeadAttention, PositionwiseFFN,
                          TransformerBlock, TransformerEncoderLayer)
from .vision import get_model

__all__ = ["vision", "get_model", "BERTModel", "BERTForPretrain", "get_bert",
           "GPT2Model", "get_gpt2", "gpt2_lm_loss", "MoELayer",
           "MoETransformerBlock", "pop_aux_losses", "StackedGPT2Model",
           "get_stacked_gpt2", "MultiHeadAttention", "PositionwiseFFN",
           "TransformerBlock", "TransformerEncoderLayer",
           "TransformerNMT", "TransformerDecoderBlock", "get_nmt",
           "nmt_loss"]
