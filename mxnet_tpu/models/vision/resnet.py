"""ResNet v1/v2 family (parity: python/mxnet/gluon/model_zoo/vision/resnet.py
— same block structure, layer counts, and layer names so checkpoints map).

TPU notes: the default NCHW layout is kept for API parity, but every
constructor accepts ``layout="NHWC"`` — the TPU-preferred channels-last
layout that keeps C on the 128-lane minor dimension through conv, BN-stat
reductions, and pooling, eliminating the relayout copies the round-3
profile showed dominating the non-conv time (docs/resnet_roofline_r05.md).
Weights are (O, I, kH, kW) in EITHER layout, so checkpoints transfer
across layouts unchanged.  BatchNorm moving stats update functionally
through the CachedOp / ShardedTrainer aux path.
"""
from __future__ import annotations

from ...gluon.block import HybridBlock
from ...gluon import nn

__all__ = ["ResNetV1", "ResNetV2", "BasicBlockV1", "BasicBlockV2",
           "BottleneckV1", "BottleneckV2", "resnet18_v1", "resnet34_v1",
           "resnet50_v1", "resnet101_v1", "resnet152_v1", "resnet18_v2",
           "resnet34_v2", "resnet50_v2", "resnet101_v2", "resnet152_v2",
           "get_resnet"]


def _bn_axis(layout):
    return -1 if layout[-1] == "C" else 1


def _conv3x3(channels, stride, in_channels, layout="NCHW"):
    return nn.Conv2D(channels, kernel_size=3, strides=stride, padding=1,
                     use_bias=False, in_channels=in_channels, layout=layout)


class BasicBlockV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        ax = _bn_axis(layout)
        self.body = nn.HybridSequential()
        self.body.add(_conv3x3(channels, stride, in_channels, layout))
        self.body.add(nn.BatchNorm(axis=ax))
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv3x3(channels, 1, channels, layout))
        self.body.add(nn.BatchNorm(axis=ax))
        if downsample:
            self.downsample = nn.HybridSequential()
            self.downsample.add(nn.Conv2D(
                channels, kernel_size=1, strides=stride, use_bias=False,
                in_channels=in_channels, layout=layout))
            self.downsample.add(nn.BatchNorm(axis=ax))
        else:
            self.downsample = None

    def forward(self, x):
        from ...ndarray import ops as F
        residual = x
        x = self.body(x)
        if self.downsample is not None:
            residual = self.downsample(residual)
        return F.Activation(x + residual, act_type="relu")


class BottleneckV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        ax = _bn_axis(layout)
        self.body = nn.HybridSequential()
        self.body.add(nn.Conv2D(channels // 4, kernel_size=1, strides=stride,
                                layout=layout))
        self.body.add(nn.BatchNorm(axis=ax))
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv3x3(channels // 4, 1, channels // 4, layout))
        self.body.add(nn.BatchNorm(axis=ax))
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, kernel_size=1, strides=1,
                                layout=layout))
        self.body.add(nn.BatchNorm(axis=ax))
        if downsample:
            self.downsample = nn.HybridSequential()
            self.downsample.add(nn.Conv2D(
                channels, kernel_size=1, strides=stride, use_bias=False,
                in_channels=in_channels, layout=layout))
            self.downsample.add(nn.BatchNorm(axis=ax))
        else:
            self.downsample = None

    def forward(self, x):
        from ...ndarray import ops as F
        residual = x
        x = self.body(x)
        if self.downsample is not None:
            residual = self.downsample(residual)
        return F.Activation(x + residual, act_type="relu")


class BasicBlockV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        ax = _bn_axis(layout)
        self.bn1 = nn.BatchNorm(axis=ax)
        self.conv1 = _conv3x3(channels, stride, in_channels, layout)
        self.bn2 = nn.BatchNorm(axis=ax)
        self.conv2 = _conv3x3(channels, 1, channels, layout)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels,
                                        layout=layout)
        else:
            self.downsample = None

    def forward(self, x):
        from ...ndarray import ops as F
        residual = x
        x = self.bn1(x)
        x = F.Activation(x, act_type="relu")
        if self.downsample is not None:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        return x + residual


class BottleneckV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        ax = _bn_axis(layout)
        self.bn1 = nn.BatchNorm(axis=ax)
        self.conv1 = nn.Conv2D(channels // 4, 1, 1, use_bias=False,
                               layout=layout)
        self.bn2 = nn.BatchNorm(axis=ax)
        self.conv2 = _conv3x3(channels // 4, stride, channels // 4, layout)
        self.bn3 = nn.BatchNorm(axis=ax)
        self.conv3 = nn.Conv2D(channels, 1, 1, use_bias=False, layout=layout)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels,
                                        layout=layout)
        else:
            self.downsample = None

    def forward(self, x):
        from ...ndarray import ops as F
        residual = x
        x = self.bn1(x)
        x = F.Activation(x, act_type="relu")
        if self.downsample is not None:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        x = self.bn3(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv3(x)
        return x + residual


class ResNetV1(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        self._layout = layout
        ax = _bn_axis(layout)
        self.features = nn.HybridSequential()
        if thumbnail:
            self.features.add(_conv3x3(channels[0], 1, 0, layout))
        else:
            self.features.add(nn.Conv2D(channels[0], 7, 2, 3,
                                        use_bias=False, layout=layout))
            self.features.add(nn.BatchNorm(axis=ax))
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(3, 2, 1, layout=layout))
        for i, num_layer in enumerate(layers):
            stride = 1 if i == 0 else 2
            self.features.add(self._make_layer(
                block, num_layer, channels[i + 1], stride,
                in_channels=channels[i]))
        self.features.add(nn.GlobalAvgPool2D(layout=layout))
        self.output = nn.Dense(classes, in_units=channels[-1])

    def _make_layer(self, block, layers, channels, stride, in_channels=0):
        layer = nn.HybridSequential()
        layer.add(block(channels, stride, channels != in_channels,
                        in_channels=in_channels, layout=self._layout))
        for _ in range(layers - 1):
            layer.add(block(channels, 1, False, in_channels=channels,
                            layout=self._layout))
        return layer

    def forward(self, x):
        x = self.features(x)
        return self.output(x)


class ResNetV2(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        self._layout = layout
        ax = _bn_axis(layout)
        self.features = nn.HybridSequential()
        self.features.add(nn.BatchNorm(axis=ax, scale=False, center=False))
        if thumbnail:
            self.features.add(_conv3x3(channels[0], 1, 0, layout))
        else:
            self.features.add(nn.Conv2D(channels[0], 7, 2, 3,
                                        use_bias=False, layout=layout))
            self.features.add(nn.BatchNorm(axis=ax))
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(3, 2, 1, layout=layout))
        in_channels = channels[0]
        for i, num_layer in enumerate(layers):
            stride = 1 if i == 0 else 2
            self.features.add(self._make_layer(
                block, num_layer, channels[i + 1], stride,
                in_channels=in_channels))
            in_channels = channels[i + 1]
        self.features.add(nn.BatchNorm(axis=ax))
        self.features.add(nn.Activation("relu"))
        self.features.add(nn.GlobalAvgPool2D(layout=layout))
        self.output = nn.Dense(classes, in_units=channels[-1])

    def _make_layer(self, block, layers, channels, stride, in_channels=0):
        layer = nn.HybridSequential()
        layer.add(block(channels, stride, channels != in_channels,
                        in_channels=in_channels, layout=self._layout))
        for _ in range(layers - 1):
            layer.add(block(channels, 1, False, in_channels=channels,
                            layout=self._layout))
        return layer

    def forward(self, x):
        x = self.features(x)
        return self.output(x)


_resnet_spec = {
    18: ("basic_block", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: ("basic_block", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: ("bottle_neck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: ("bottle_neck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: ("bottle_neck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048]),
}
_v1_blocks = {"basic_block": BasicBlockV1, "bottle_neck": BottleneckV1}
_v2_blocks = {"basic_block": BasicBlockV2, "bottle_neck": BottleneckV2}


def get_resnet(version, num_layers, pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights are not bundled (no model download in this "
            "environment); load_parameters() an MXNet checkpoint instead")
    block_type, layers, channels = _resnet_spec[num_layers]
    if version == 1:
        return ResNetV1(_v1_blocks[block_type], layers, channels, **kwargs)
    return ResNetV2(_v2_blocks[block_type], layers, channels, **kwargs)


def _make(version, n):
    def f(**kwargs):
        return get_resnet(version, n, **kwargs)
    f.__name__ = f"resnet{n}_v{version}"
    return f


resnet18_v1 = _make(1, 18)
resnet34_v1 = _make(1, 34)
resnet50_v1 = _make(1, 50)
resnet101_v1 = _make(1, 101)
resnet152_v1 = _make(1, 152)
resnet18_v2 = _make(2, 18)
resnet34_v2 = _make(2, 34)
resnet50_v2 = _make(2, 50)
resnet101_v2 = _make(2, 101)
resnet152_v2 = _make(2, 152)
