"""AlexNet (parity: python/mxnet/gluon/model_zoo/vision/alexnet.py —
same features/output split and layer order so checkpoints map)."""
from __future__ import annotations

from ...gluon import nn
from ...gluon.block import HybridBlock

__all__ = ["AlexNet", "alexnet"]


class AlexNet(HybridBlock):
    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential()
        self.features.add(
            nn.Conv2D(64, kernel_size=11, strides=4, padding=2,
                      activation="relu"),
            nn.MaxPool2D(pool_size=3, strides=2),
            nn.Conv2D(192, kernel_size=5, padding=2, activation="relu"),
            nn.MaxPool2D(pool_size=3, strides=2),
            nn.Conv2D(384, kernel_size=3, padding=1, activation="relu"),
            nn.Conv2D(256, kernel_size=3, padding=1, activation="relu"),
            nn.Conv2D(256, kernel_size=3, padding=1, activation="relu"),
            nn.MaxPool2D(pool_size=3, strides=2),
            nn.Flatten(),
            nn.Dense(4096, activation="relu"),
            nn.Dropout(0.5),
            nn.Dense(4096, activation="relu"),
            nn.Dropout(0.5))
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


def alexnet(**kwargs):
    return AlexNet(**kwargs)
