"""Vision model zoo (parity: python/mxnet/gluon/model_zoo/vision)."""
from .alexnet import AlexNet, alexnet
from .densenet import (DenseNet, densenet121, densenet161, densenet169,
                       densenet201)
from .inception import Inception3, inception_v3
from .mlp import MLP
from .mobilenet import (MobileNet, MobileNetV2, mobilenet0_25, mobilenet0_5,
                        mobilenet0_75, mobilenet1_0, mobilenet_v2_0_25,
                        mobilenet_v2_0_5, mobilenet_v2_0_75,
                        mobilenet_v2_1_0)
from .resnet import (BasicBlockV1, BasicBlockV2, BottleneckV1, BottleneckV2,
                     ResNetV1, ResNetV2, get_resnet, resnet18_v1,
                     resnet18_v2, resnet34_v1, resnet34_v2, resnet50_v1,
                     resnet50_v2, resnet101_v1, resnet101_v2, resnet152_v1,
                     resnet152_v2)
from .squeezenet import SqueezeNet, squeezenet1_0, squeezenet1_1
from .vgg import (VGG, get_vgg, vgg11, vgg11_bn, vgg13, vgg13_bn, vgg16,
                  vgg16_bn, vgg19, vgg19_bn)

_models = {name: globals()[name] for name in (
    "resnet18_v1", "resnet34_v1", "resnet50_v1", "resnet101_v1",
    "resnet152_v1", "resnet18_v2", "resnet34_v2", "resnet50_v2",
    "resnet101_v2", "resnet152_v2",
    "alexnet",
    "vgg11", "vgg13", "vgg16", "vgg19",
    "vgg11_bn", "vgg13_bn", "vgg16_bn", "vgg19_bn",
    "squeezenet1_0", "squeezenet1_1",
    "densenet121", "densenet161", "densenet169", "densenet201",
    "mobilenet1_0", "mobilenet0_75", "mobilenet0_5", "mobilenet0_25",
    "mobilenet_v2_1_0", "mobilenet_v2_0_75", "mobilenet_v2_0_5",
    "mobilenet_v2_0_25",
    "inception_v3")}


def get_model(name, **kwargs):
    """Parity: gluon.model_zoo.vision.get_model."""
    name = name.lower()
    if name not in _models:
        raise ValueError(
            f"model {name} not found; available: {sorted(_models)}")
    return _models[name](**kwargs)
