"""Vision model zoo (parity: python/mxnet/gluon/model_zoo/vision)."""
from .resnet import (BasicBlockV1, BasicBlockV2, BottleneckV1, BottleneckV2,
                     ResNetV1, ResNetV2, get_resnet, resnet18_v1,
                     resnet18_v2, resnet34_v1, resnet34_v2, resnet50_v1,
                     resnet50_v2, resnet101_v1, resnet101_v2, resnet152_v1,
                     resnet152_v2)
from .mlp import MLP

_models = {name: globals()[name] for name in (
    "resnet18_v1", "resnet34_v1", "resnet50_v1", "resnet101_v1",
    "resnet152_v1", "resnet18_v2", "resnet34_v2", "resnet50_v2",
    "resnet101_v2", "resnet152_v2")}


def get_model(name, **kwargs):
    """Parity: gluon.model_zoo.vision.get_model."""
    name = name.lower()
    if name not in _models:
        raise ValueError(
            f"model {name} not found; available: {sorted(_models)}")
    return _models[name](**kwargs)
