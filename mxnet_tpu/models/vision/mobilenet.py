"""MobileNet v1 (width multipliers) and v2 (parity:
python/mxnet/gluon/model_zoo/vision/mobilenet.py — same depthwise-
separable / inverted-residual structure).

TPU note: depthwise convolutions lower to XLA's feature-group
convolution, which the TPU convolution emitter handles natively.
"""
from __future__ import annotations

from ...gluon import nn
from ...gluon.block import HybridBlock

__all__ = ["MobileNet", "MobileNetV2", "mobilenet1_0", "mobilenet0_75",
           "mobilenet0_5", "mobilenet0_25", "mobilenet_v2_1_0",
           "mobilenet_v2_0_75", "mobilenet_v2_0_5", "mobilenet_v2_0_25"]


class RELU6(HybridBlock):
    """relu6 = clip(x, 0, 6) — the canonical MobileNet activation."""

    def forward(self, x):
        from ...ndarray import ops as F
        return F.clip(x, 0.0, 6.0)


def _conv_block(out, kernel, stride, pad, groups=1, act=True, relu6=False):
    # upstream model_zoo uses plain ReLU for v1 and relu6 only for v2 —
    # ported v1 checkpoints diverge wherever activations exceed 6 otherwise
    seq = nn.HybridSequential()
    seq.add(nn.Conv2D(out, kernel_size=kernel, strides=stride, padding=pad,
                      groups=groups, use_bias=False))
    seq.add(nn.BatchNorm())
    if act:
        seq.add(RELU6() if relu6 else nn.Activation("relu"))
    return seq


class MobileNet(HybridBlock):
    """v1: conv 3x3 stem + 13 depthwise-separable blocks."""

    def __init__(self, multiplier=1.0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        def c(ch):
            return max(8, int(ch * multiplier))
        spec = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1),
                (512, 2)] + [(512, 1)] * 5 + [(1024, 2), (1024, 1)]
        self.features = nn.HybridSequential()
        self.features.add(_conv_block(c(32), 3, 2, 1))
        in_ch = c(32)
        for out, stride in spec:
            # depthwise 3x3 (groups == channels) then pointwise 1x1
            self.features.add(_conv_block(in_ch, 3, stride, 1,
                                          groups=in_ch))
            self.features.add(_conv_block(c(out), 1, 1, 0))
            in_ch = c(out)
        self.features.add(nn.GlobalAvgPool2D(), nn.Flatten())
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


class _InvertedResidual(HybridBlock):
    def __init__(self, in_ch, out_ch, stride, expansion, **kwargs):
        super().__init__(**kwargs)
        self.use_shortcut = stride == 1 and in_ch == out_ch
        mid = in_ch * expansion
        self.body = nn.HybridSequential()
        if expansion != 1:
            self.body.add(_conv_block(mid, 1, 1, 0, relu6=True))
        self.body.add(_conv_block(mid, 3, stride, 1, groups=mid,
                                  relu6=True))
        self.body.add(_conv_block(out_ch, 1, 1, 0, act=False))

    def forward(self, x):
        out = self.body(x)
        return x + out if self.use_shortcut else out


class MobileNetV2(HybridBlock):
    def __init__(self, multiplier=1.0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        def c(ch):
            return max(8, int(ch * multiplier))
        # t (expansion), c (channels), n (repeats), s (stride)
        spec = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2),
                (6, 64, 4, 2), (6, 96, 3, 1), (6, 160, 3, 2),
                (6, 320, 1, 1)]
        self.features = nn.HybridSequential()
        self.features.add(_conv_block(c(32), 3, 2, 1, relu6=True))
        in_ch = c(32)
        for t, ch, n, s in spec:
            for i in range(n):
                self.features.add(_InvertedResidual(
                    in_ch, c(ch), s if i == 0 else 1, t))
                in_ch = c(ch)
        last = 1280 if multiplier <= 1.0 else c(1280)
        self.features.add(_conv_block(last, 1, 1, 0, relu6=True))
        self.features.add(nn.GlobalAvgPool2D(), nn.Flatten())
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


def _v1(mult):
    def f(**kw):
        return MobileNet(mult, **kw)
    return f


def _v2(mult):
    def f(**kw):
        return MobileNetV2(mult, **kw)
    return f


mobilenet1_0 = _v1(1.0)
mobilenet0_75 = _v1(0.75)
mobilenet0_5 = _v1(0.5)
mobilenet0_25 = _v1(0.25)
mobilenet_v2_1_0 = _v2(1.0)
mobilenet_v2_0_75 = _v2(0.75)
mobilenet_v2_0_5 = _v2(0.5)
mobilenet_v2_0_25 = _v2(0.25)
