"""MNIST-scale MLP (the minimum end-to-end slice model, SURVEY.md §7.4)."""
from ...gluon import nn
from ...gluon.block import HybridBlock


class MLP(HybridBlock):
    def __init__(self, hidden=(128, 64), classes=10, activation="relu",
                 **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential()
        for h in hidden:
            self.body.add(nn.Dense(h, activation=activation))
        self.body.add(nn.Dense(classes))

    def forward(self, x):
        return self.body(x)
