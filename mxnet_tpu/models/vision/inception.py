"""Inception v3 (parity: python/mxnet/gluon/model_zoo/vision/inception.py —
same block structure: A (35x35), B (17x17 with factorized 7x1/1x7), C
(8x8 with expanded branches), and the two grid reductions).

TPU note: every branch is standard NCHW conv+BN+ReLU lowered to
``lax.conv_general_dilated``; branch outputs concatenate on the channel
axis, which XLA fuses with the adjacent convs' epilogues.
"""
from __future__ import annotations

from ...gluon import nn
from ...gluon.block import HybridBlock
from ...ndarray import ops as F

__all__ = ["Inception3", "inception_v3"]


def _conv(channels, kernel, stride=1, pad=0):
    seq = nn.HybridSequential()
    seq.add(nn.Conv2D(channels, kernel_size=kernel, strides=stride,
                      padding=pad, use_bias=False))
    seq.add(nn.BatchNorm(epsilon=0.001))
    seq.add(nn.Activation("relu"))
    return seq


class _Branches(HybridBlock):
    """Run N branches on the same input and concat channels."""

    def __init__(self, branches, **kwargs):
        super().__init__(**kwargs)
        self.branches = []
        for i, b in enumerate(branches):
            self.register_child(b, f"b{i}")
            self.branches.append(b)

    def forward(self, x):
        return F.concat(*[b(x) for b in self.branches], dim=1)


def _pool_branch(channels, avg=True):
    seq = nn.HybridSequential()
    if avg:
        seq.add(nn.AvgPool2D(pool_size=3, strides=1, padding=1))
    else:
        seq.add(nn.MaxPool2D(pool_size=3, strides=1, padding=1))
    if channels:
        seq.add(_conv(channels, 1))
    return seq


def _seq(*blocks):
    s = nn.HybridSequential()
    s.add(*blocks)
    return s


def _make_A(pool_features):
    return _Branches([
        _conv(64, 1),
        _seq(_conv(48, 1), _conv(64, 5, pad=2)),
        _seq(_conv(64, 1), _conv(96, 3, pad=1), _conv(96, 3, pad=1)),
        _pool_branch(pool_features),
    ])


class _ReductionA(HybridBlock):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.c3 = _conv(384, 3, stride=2)
        self.c3d = _seq(_conv(64, 1), _conv(96, 3, pad=1),
                        _conv(96, 3, stride=2))
        self.pool = nn.MaxPool2D(pool_size=3, strides=2)

    def forward(self, x):
        return F.concat(self.c3(x), self.c3d(x), self.pool(x), dim=1)


def _make_B(c7):
    return _Branches([
        _conv(192, 1),
        _seq(_conv(c7, 1), _conv(c7, (1, 7), pad=(0, 3)),
             _conv(192, (7, 1), pad=(3, 0))),
        _seq(_conv(c7, 1), _conv(c7, (7, 1), pad=(3, 0)),
             _conv(c7, (1, 7), pad=(0, 3)), _conv(c7, (7, 1), pad=(3, 0)),
             _conv(192, (1, 7), pad=(0, 3))),
        _pool_branch(192),
    ])


class _ReductionB(HybridBlock):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.b1 = _seq(_conv(192, 1), _conv(320, 3, stride=2))
        self.b2 = _seq(_conv(192, 1), _conv(192, (1, 7), pad=(0, 3)),
                       _conv(192, (7, 1), pad=(3, 0)),
                       _conv(192, 3, stride=2))
        self.pool = nn.MaxPool2D(pool_size=3, strides=2)

    def forward(self, x):
        return F.concat(self.b1(x), self.b2(x), self.pool(x), dim=1)


class _InceptionC(HybridBlock):
    """8x8 block: the 3x3 branches split into parallel 1x3/3x1 halves."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.b0 = _conv(320, 1)
        self.b1_stem = _conv(384, 1)
        self.b1_a = _conv(384, (1, 3), pad=(0, 1))
        self.b1_b = _conv(384, (3, 1), pad=(1, 0))
        self.b2_stem = _seq(_conv(448, 1), _conv(384, 3, pad=1))
        self.b2_a = _conv(384, (1, 3), pad=(0, 1))
        self.b2_b = _conv(384, (3, 1), pad=(1, 0))
        self.bp = _pool_branch(192)

    def forward(self, x):
        s1 = self.b1_stem(x)
        s2 = self.b2_stem(x)
        return F.concat(self.b0(x), self.b1_a(s1), self.b1_b(s1),
                        self.b2_a(s2), self.b2_b(s2), self.bp(x), dim=1)


class Inception3(HybridBlock):
    """Inception v3 (299x299 canonical input; any >=75px works)."""

    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential()
        self.features.add(
            _conv(32, 3, stride=2),
            _conv(32, 3),
            _conv(64, 3, pad=1),
            nn.MaxPool2D(pool_size=3, strides=2),
            _conv(80, 1),
            _conv(192, 3),
            nn.MaxPool2D(pool_size=3, strides=2),
            _make_A(32), _make_A(64), _make_A(64),
            _ReductionA(),
            _make_B(128), _make_B(160), _make_B(160), _make_B(192),
            _ReductionB(),
            _InceptionC(), _InceptionC(),
            nn.GlobalAvgPool2D(),
            nn.Dropout(0.5),
            nn.Flatten(),
        )
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


def inception_v3(**kwargs):
    """Parity: model_zoo.vision.inception_v3."""
    return Inception3(**kwargs)
