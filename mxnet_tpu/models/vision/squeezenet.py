"""SqueezeNet 1.0/1.1 (parity:
python/mxnet/gluon/model_zoo/vision/squeezenet.py — fire-module
structure and version layouts)."""
from __future__ import annotations

from ...gluon import nn
from ...gluon.block import HybridBlock

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1"]


class _Fire(HybridBlock):
    def __init__(self, squeeze, expand1x1, expand3x3, **kwargs):
        super().__init__(**kwargs)
        self.squeeze = nn.Conv2D(squeeze, kernel_size=1, activation="relu")
        self.expand1x1 = nn.Conv2D(expand1x1, kernel_size=1,
                                   activation="relu")
        self.expand3x3 = nn.Conv2D(expand3x3, kernel_size=3, padding=1,
                                   activation="relu")

    def forward(self, x):
        from ...ndarray import ops as F
        x = self.squeeze(x)
        return F.concat(self.expand1x1(x), self.expand3x3(x), dim=1)


class SqueezeNet(HybridBlock):
    def __init__(self, version="1.0", classes=1000, **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential()
        if version == "1.0":
            self.features.add(
                nn.Conv2D(96, kernel_size=7, strides=2, activation="relu"),
                nn.MaxPool2D(pool_size=3, strides=2, ceil_mode=True),
                _Fire(16, 64, 64), _Fire(16, 64, 64), _Fire(32, 128, 128),
                nn.MaxPool2D(pool_size=3, strides=2, ceil_mode=True),
                _Fire(32, 128, 128), _Fire(48, 192, 192),
                _Fire(48, 192, 192), _Fire(64, 256, 256),
                nn.MaxPool2D(pool_size=3, strides=2, ceil_mode=True),
                _Fire(64, 256, 256))
        elif version == "1.1":
            self.features.add(
                nn.Conv2D(64, kernel_size=3, strides=2, activation="relu"),
                nn.MaxPool2D(pool_size=3, strides=2, ceil_mode=True),
                _Fire(16, 64, 64), _Fire(16, 64, 64),
                nn.MaxPool2D(pool_size=3, strides=2, ceil_mode=True),
                _Fire(32, 128, 128), _Fire(32, 128, 128),
                nn.MaxPool2D(pool_size=3, strides=2, ceil_mode=True),
                _Fire(48, 192, 192), _Fire(48, 192, 192),
                _Fire(64, 256, 256), _Fire(64, 256, 256))
        else:
            raise ValueError(f"unsupported SqueezeNet version {version}")
        self.features.add(nn.Dropout(0.5))
        self.output = nn.HybridSequential()
        self.output.add(nn.Conv2D(classes, kernel_size=1,
                                  activation="relu"),
                        nn.GlobalAvgPool2D(),
                        nn.Flatten())

    def forward(self, x):
        return self.output(self.features(x))


def squeezenet1_0(**kw):
    return SqueezeNet("1.0", **kw)


def squeezenet1_1(**kw):
    return SqueezeNet("1.1", **kw)
