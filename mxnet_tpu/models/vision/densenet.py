"""DenseNet 121/161/169/201 (parity:
python/mxnet/gluon/model_zoo/vision/densenet.py — same growth-rate /
block-config tables and dense/transition structure)."""
from __future__ import annotations

from ...gluon import nn
from ...gluon.block import HybridBlock

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201"]

# num_init_features, growth_rate, block_config
_SPEC = {
    121: (64, 32, [6, 12, 24, 16]),
    161: (96, 48, [6, 12, 36, 24]),
    169: (64, 32, [6, 12, 32, 32]),
    201: (64, 32, [6, 12, 48, 32]),
}


class _DenseLayer(HybridBlock):
    def __init__(self, growth_rate, bn_size, dropout, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential()
        self.body.add(nn.BatchNorm(), nn.Activation("relu"),
                      nn.Conv2D(bn_size * growth_rate, kernel_size=1,
                                use_bias=False),
                      nn.BatchNorm(), nn.Activation("relu"),
                      nn.Conv2D(growth_rate, kernel_size=3, padding=1,
                                use_bias=False))
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        from ...ndarray import ops as F
        out = self.body(x)
        if self.dropout is not None:
            out = self.dropout(out)
        return F.concat(x, out, dim=1)


def _transition(num_output):
    out = nn.HybridSequential()
    out.add(nn.BatchNorm(), nn.Activation("relu"),
            nn.Conv2D(num_output, kernel_size=1, use_bias=False),
            nn.AvgPool2D(pool_size=2, strides=2))
    return out


class DenseNet(HybridBlock):
    def __init__(self, num_init_features, growth_rate, block_config,
                 bn_size=4, dropout=0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential()
        self.features.add(
            nn.Conv2D(num_init_features, kernel_size=7, strides=2,
                      padding=3, use_bias=False),
            nn.BatchNorm(), nn.Activation("relu"),
            nn.MaxPool2D(pool_size=3, strides=2, padding=1))
        channels = num_init_features
        for i, n in enumerate(block_config):
            block = nn.HybridSequential()
            for _ in range(n):
                block.add(_DenseLayer(growth_rate, bn_size, dropout))
            self.features.add(block)
            channels += n * growth_rate
            if i != len(block_config) - 1:
                channels //= 2
                self.features.add(_transition(channels))
        self.features.add(nn.BatchNorm(), nn.Activation("relu"),
                          nn.GlobalAvgPool2D(), nn.Flatten())
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


def _make(n):
    def f(**kw):
        init, growth, cfg = _SPEC[n]
        return DenseNet(init, growth, cfg, **kw)
    f.__name__ = f"densenet{n}"
    return f


densenet121 = _make(121)
densenet161 = _make(161)
densenet169 = _make(169)
densenet201 = _make(201)
