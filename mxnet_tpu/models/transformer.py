"""Transformer building blocks with first-class tensor/sequence parallelism.

Capability parity: MXNet's transformer support was GluonNLP-side Python over
the fused contrib matmuls (src/operator/contrib/transformer.cc); there was
no TP/SP (SURVEY.md §2.4 row "Parallelism strategies").  Here every layer
carries logical sharding axes (Megatron-style: attention heads and FFN hidden
over ``tp``, sequence over ``sp``) so the same Block runs single-chip or
SPMD over a mesh without code changes.
"""
from __future__ import annotations

import math
from typing import Optional

from ..gluon.block import HybridBlock
from ..gluon.nn import Dense, Dropout, GELU, LayerNorm
from ..ops import dot_product_attention
from ..parallel.sharding import annotate
from .. import parallel as _par


class MultiHeadAttention(HybridBlock):
    """Self-attention with per-head tensor parallelism.

    q/k/v/out projections are separate Dense layers so the ``tp`` sharding
    of the ``units`` dim splits along head boundaries (Megatron column/row
    parallel); attention math runs through ops.dot_product_attention
    (Pallas flash kernel on TPU for long sequences).
    """

    def __init__(self, units, num_heads, dropout=0.0, attention_dropout=0.0,
                 use_bias=True, causal=False, **kwargs):
        super().__init__(**kwargs)
        if units % num_heads:
            raise ValueError(f"units {units} not divisible by heads "
                             f"{num_heads}")
        self._units = units
        self._num_heads = num_heads
        self._head_dim = units // num_heads
        self._causal = causal
        self._att_dropout = attention_dropout
        for name in ("q_proj", "k_proj", "v_proj"):
            d = Dense(units, use_bias=use_bias, flatten=False,
                      in_units=units)
            annotate(d.weight, "heads", "embed")
            if d.bias is not None:
                annotate(d.bias, "heads")
            setattr(self, name, d)
        self.out_proj = Dense(units, use_bias=use_bias, flatten=False,
                              in_units=units)
        annotate(self.out_proj.weight, "embed", "heads")
        if self.out_proj.bias is not None:
            annotate(self.out_proj.bias, "norm")
        self.dropout = Dropout(dropout) if dropout else None

    def forward(self, x, mask=None):
        b, t = x.shape[0], x.shape[1]
        h, d = self._num_heads, self._head_dim
        q = self.q_proj(x).reshape((b, t, h, d))
        k = self.k_proj(x).reshape((b, t, h, d))
        v = self.v_proj(x).reshape((b, t, h, d))
        mesh = _par.current_mesh()
        sp = _par.axis_size(mesh, "sp") if mesh is not None else 1
        # shard_map needs every sharded dim to divide its mesh axis —
        # uneven shapes (e.g. a last odd-sized batch) keep the GSPMD path
        divisible = (sp > 1 and isinstance(t, int) and t % sp == 0
                     and b % _par.axis_size(mesh, "dp") == 0
                     and h % _par.axis_size(mesh, "tp") == 0)
        if divisible and mask is None and self._att_dropout == 0.0:
            # sequence parallel: K/V chunks ride the ICI ring instead of
            # an all-gather of the full sequence per device
            from ..ops import nd_ring_attention
            out = nd_ring_attention(q, k, v, causal=self._causal, mesh=mesh)
        else:
            out = dot_product_attention(
                q, k, v, causal=self._causal, mask=mask,
                dropout=self._att_dropout)
        out = _par.with_sharding_constraint(out, "batch", "seq", "heads",
                                            None)
        out = self.out_proj(out.reshape((b, t, h * d)))
        if self.dropout is not None:
            out = self.dropout(out)
        return out


class PositionwiseFFN(HybridBlock):
    """Transformer FFN: Dense(hidden) → GELU → Dense(units), hidden sharded
    over ``tp`` (Megatron column then row parallel)."""

    def __init__(self, units, hidden_size, dropout=0.0, activation="gelu",
                 use_bias=True, **kwargs):
        super().__init__(**kwargs)
        self.fc1 = Dense(hidden_size, use_bias=use_bias, flatten=False,
                         in_units=units)
        annotate(self.fc1.weight, "mlp", "embed")
        if self.fc1.bias is not None:
            annotate(self.fc1.bias, "mlp")
        self.act = GELU() if activation == "gelu" else None
        self._activation = activation
        self.fc2 = Dense(units, use_bias=use_bias, flatten=False,
                         in_units=hidden_size)
        annotate(self.fc2.weight, "embed", "mlp")
        self.dropout = Dropout(dropout) if dropout else None

    def forward(self, x):
        from ..ndarray import ops as F
        h = self.fc1(x)
        h = self.act(h) if self.act is not None else \
            F.Activation(h, act_type=self._activation)
        h = self.fc2(h)
        if self.dropout is not None:
            h = self.dropout(h)
        return h


class TransformerBlock(HybridBlock):
    """Pre-LN transformer layer (GPT-2 style)."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 attention_dropout=0.0, causal=True, layer_norm_eps=1e-5,
                 **kwargs):
        super().__init__(**kwargs)
        self.ln1 = LayerNorm(epsilon=layer_norm_eps, in_channels=units)
        self.attn = MultiHeadAttention(
            units, num_heads, dropout=dropout,
            attention_dropout=attention_dropout, causal=causal)
        self.ln2 = LayerNorm(epsilon=layer_norm_eps, in_channels=units)
        self.ffn = PositionwiseFFN(units, hidden_size, dropout=dropout)

    def forward(self, x, mask=None):
        x = x + self.attn(self.ln1(x), mask)
        x = _par.with_sharding_constraint(x, "batch", "seq", None)
        x = x + self.ffn(self.ln2(x))
        return _par.with_sharding_constraint(x, "batch", "seq", None)


class TransformerEncoderLayer(TransformerBlock):
    """Bidirectional (BERT-style) layer: post-LN off, no causal mask."""

    def __init__(self, units, hidden_size, num_heads, **kwargs):
        super().__init__(units, hidden_size, num_heads, causal=False,
                         **kwargs)
