"""Transformer building blocks with first-class tensor/sequence parallelism.

Capability parity: MXNet's transformer support was GluonNLP-side Python over
the fused contrib matmuls (src/operator/contrib/transformer.cc); there was
no TP/SP (SURVEY.md §2.4 row "Parallelism strategies").  Here every layer
carries logical sharding axes (Megatron-style: attention heads and FFN hidden
over ``tp``, sequence over ``sp``) so the same Block runs single-chip or
SPMD over a mesh without code changes.
"""
from __future__ import annotations

import math
from typing import Optional

from ..gluon.block import HybridBlock
from ..gluon.nn import Dense, Dropout, GELU, LayerNorm
from ..ops import dot_product_attention
from ..parallel.sharding import annotate
from .. import parallel as _par

_WARNED_ULYSSES_FALLBACK = False


class MultiHeadAttention(HybridBlock):
    """Self-attention with per-head tensor parallelism.

    q/k/v/out projections are separate Dense layers so the ``tp`` sharding
    of the ``units`` dim splits along head boundaries (Megatron column/row
    parallel); attention math runs through ops.dot_product_attention
    (Pallas flash kernel on TPU for long sequences).
    """

    def __init__(self, units, num_heads, dropout=0.0, attention_dropout=0.0,
                 use_bias=True, causal=False, seq_parallel=None, **kwargs):
        super().__init__(**kwargs)
        if units % num_heads:
            raise ValueError(f"units {units} not divisible by heads "
                             f"{num_heads}")
        if seq_parallel is None:
            import os
            seq_parallel = os.environ.get("MXNET_TPU_SEQ_PARALLEL", "ring")
        if seq_parallel not in ("ring", "ulysses"):
            raise ValueError(
                f"seq_parallel must be 'ring' or 'ulysses', "
                f"got {seq_parallel!r}")
        self._seq_parallel = seq_parallel
        self._units = units
        self._num_heads = num_heads
        self._head_dim = units // num_heads
        self._causal = causal
        self._att_dropout = attention_dropout
        for name in ("q_proj", "k_proj", "v_proj"):
            d = Dense(units, use_bias=use_bias, flatten=False,
                      in_units=units)
            annotate(d.weight, "heads", "embed")
            if d.bias is not None:
                annotate(d.bias, "heads")
            setattr(self, name, d)
        self.out_proj = Dense(units, use_bias=use_bias, flatten=False,
                              in_units=units)
        annotate(self.out_proj.weight, "embed", "heads")
        if self.out_proj.bias is not None:
            annotate(self.out_proj.bias, "norm")
        self.dropout = Dropout(dropout) if dropout else None

    def forward(self, x, mask=None, memory=None):
        """Self-attention over ``x``; cross-attention when ``memory`` is
        given (queries from ``x``, keys/values from ``memory`` — the
        encoder-decoder attention of Sockeye-style NMT)."""
        b, t = x.shape[0], x.shape[1]
        h, d = self._num_heads, self._head_dim
        kv = x if memory is None else memory
        tk = kv.shape[1]
        q = self.q_proj(x).reshape((b, t, h, d))
        k = self.k_proj(kv).reshape((b, tk, h, d))
        v = self.v_proj(kv).reshape((b, tk, h, d))
        mesh = _par.current_mesh()
        sp = _par.axis_size(mesh, "sp") if mesh is not None else 1
        # shard_map needs every sharded dim to divide its mesh axis —
        # uneven shapes (e.g. a last odd-sized batch) keep the GSPMD path
        divisible = (sp > 1 and isinstance(t, int) and t % sp == 0
                     and b % _par.axis_size(mesh, "dp") == 0
                     and h % _par.axis_size(mesh, "tp") == 0)
        if divisible and mask is None and memory is None \
                and self._att_dropout == 0.0:
            # sequence parallel: either K/V chunks ride the ICI ring, or
            # (Ulysses) two all-to-alls re-shard seq<->heads so each
            # device runs FULL-sequence flash attention on its head group
            if self._seq_parallel == "ulysses":
                if (h // _par.axis_size(mesh, "tp")) % sp == 0:
                    from ..ops import nd_ulysses_attention
                    out = nd_ulysses_attention(q, k, v,
                                               causal=self._causal,
                                               mesh=mesh)
                else:
                    global _WARNED_ULYSSES_FALLBACK
                    if not _WARNED_ULYSSES_FALLBACK:
                        import logging
                        logging.warning(
                            "seq_parallel='ulysses' needs local heads "
                            "(%d/|tp|) divisible by |sp|=%d; falling "
                            "back to ring attention", h, sp)
                        _WARNED_ULYSSES_FALLBACK = True
                    from ..ops import nd_ring_attention
                    out = nd_ring_attention(q, k, v, causal=self._causal,
                                            mesh=mesh)
            else:
                from ..ops import nd_ring_attention
                out = nd_ring_attention(q, k, v, causal=self._causal,
                                        mesh=mesh)
        else:
            out = dot_product_attention(
                q, k, v, causal=self._causal, mask=mask,
                dropout=self._att_dropout)
        out = _par.with_sharding_constraint(out, "batch", "seq", "heads",
                                            None)
        out = self.out_proj(out.reshape((b, t, h * d)))
        if self.dropout is not None:
            out = self.dropout(out)
        return out

    def forward_step(self, x, cache, idx):
        """Incremental decode: x (B,1,U) at position ``idx`` against the
        KV cache {'k','v': (B,Tmax,H,D) jax arrays}.  Returns
        (out (B,1,U), new cache).  Inference only (no dropout)."""
        import jax

        from ..ndarray import NDArray

        b = x.shape[0]
        h, d = self._num_heads, self._head_dim
        q = self.q_proj(x).reshape((b, 1, h, d))
        k_new = self.k_proj(x).reshape((b, 1, h, d))
        v_new = self.v_proj(x).reshape((b, 1, h, d))
        kc = jax.lax.dynamic_update_slice(
            cache["k"], k_new.jax.astype(cache["k"].dtype), (0, idx, 0, 0))
        vc = jax.lax.dynamic_update_slice(
            cache["v"], v_new.jax.astype(cache["v"].dtype), (0, idx, 0, 0))
        out = _attention_step(q.jax, kc, vc, idx, 1.0 / (d ** 0.5))
        out = self.out_proj(NDArray(out.reshape(b, 1, h * d)))
        return out, {"k": kc, "v": vc}

    def forward_prefill(self, x, cache):
        """Batched cache fill: full causal attention over the prompt
        (B,T,U) in ONE pass, writing K/V for positions [0, T) into the
        cache.  Inference only."""
        import jax

        from ..ndarray import NDArray
        from ..ops import dot_product_attention

        b, t = x.shape[0], x.shape[1]
        h, d = self._num_heads, self._head_dim
        q = self.q_proj(x).reshape((b, t, h, d))
        k = self.k_proj(x).reshape((b, t, h, d))
        v = self.v_proj(x).reshape((b, t, h, d))
        kc = jax.lax.dynamic_update_slice(
            cache["k"], k.jax.astype(cache["k"].dtype), (0, 0, 0, 0))
        vc = jax.lax.dynamic_update_slice(
            cache["v"], v.jax.astype(cache["v"].dtype), (0, 0, 0, 0))
        out = dot_product_attention(q, k, v, causal=True)
        out = self.out_proj(out.reshape((b, t, h * d)))
        return out, {"k": kc, "v": vc}

    def forward_step_slots(self, x, cache, pos, page_table=None,
                           paged_kernel=False):
        """Continuous-batching decode: x (S,1,U) where row s is an
        independent request parked in SLOT s of the persistent cache
        {'k','v': (R,Tmax,H,D)}, at its OWN position ``pos`` (S,) int32.
        Writes K/V at [s, pos[s]] and attends row-wise to keys
        <= pos[s].  The cache may carry MORE rows than the decode batch
        (R >= S: the scratch and prefix-pool rows live past the slots) —
        only rows [0, S) are written or attended; an out-of-range
        ``pos`` (the engine parks idle rows at Tmax) makes the write an
        out-of-bounds scatter, which jax DROPS, so idle rows never
        clobber cache state.  Inference only.

        PAGED variant (``page_table`` (S, P) int32 given — docs/
        serving.md "Paged KV"): the cache is {'k','v': (N+1, ps, H, D)}
        pages instead of rows; row s's write routes through its table
        entry ``page_table[s, pos[s]//ps]`` (parked rows and writes
        into unassigned table entries route OUT OF BOUNDS, which jax
        drops — page N is the never-written ZERO page that unassigned
        entries READ).  With ``paged_kernel=True`` attention reads the
        pages IN PLACE through the table (:func:`mxnet_tpu.ops.paged.
        paged_attention`); otherwise the row's pages are gathered back
        into a contiguous (S, P*ps, H, D) view so the masked attention
        below is shared verbatim with the dense layout — identical
        shapes, identical masked values, bit-identical tokens (the
        kernel arm matches token-for-token; its online softmax
        reassociates the reduction, so bits may differ).

        QUANTIZED variant (the cache carries ``k_scale``/``v_scale``
        leaves — docs/serving.md "Quantized KV"): new K/V quantize to
        int8 on the scatter write with per-position-per-head fp32
        scales landing beside them (same routing, so targetless scale
        writes drop identically), and dequantize at attention time —
        fused into the kernel's tile loads, or broadcast-multiplied
        after the gather on the reference arm."""
        import jax.numpy as jnp

        from ..ndarray import NDArray
        from ..ops.paged import kv_quantize, paged_attention

        s = x.shape[0]
        h, d = self._num_heads, self._head_dim
        q = self.q_proj(x).reshape((s, 1, h, d))
        k_new = self.k_proj(x).reshape((s, h, d))
        v_new = self.v_proj(x).reshape((s, h, d))
        if page_table is None:
            rows = jnp.arange(s)
            kc = cache["k"].at[rows, pos].set(
                k_new.jax.astype(cache["k"].dtype))
            vc = cache["v"].at[rows, pos].set(
                v_new.jax.astype(cache["v"].dtype))
            krow, vrow = kc[:s], vc[:s]
        else:
            ps = cache["k"].shape[1]
            tmax = page_table.shape[1] * ps
            zero_page = cache["k"].shape[0] - 1
            lp = jnp.minimum(pos // ps, page_table.shape[1] - 1)
            mapped = page_table[jnp.arange(s), lp]
            # a write with no real target — a parked row (pos >= Tmax)
            # or an unassigned table entry (zero page) — routes OUT OF
            # BOUNDS so jax DROPS it.  Nothing may ever write the zero
            # page: unassigned logical pages of every live slot read
            # it, so one row's NaN landing there would poison every
            # other row through the 0·NaN=NaN value einsum (the dense
            # layout isolates rows; paging must too)
            phys = jnp.where((pos < tmax) & (mapped != zero_page),
                             mapped, zero_page + 1)
            off = pos % ps
            quant = "k_scale" in cache
            if quant:
                kq, ksc = kv_quantize(k_new.jax)
                vq, vsc = kv_quantize(v_new.jax)
                kc = cache["k"].at[phys, off].set(kq)
                vc = cache["v"].at[phys, off].set(vq)
                ks_c = cache["k_scale"].at[phys, off].set(ksc)
                vs_c = cache["v_scale"].at[phys, off].set(vsc)
            else:
                kc = cache["k"].at[phys, off].set(
                    k_new.jax.astype(cache["k"].dtype))
                vc = cache["v"].at[phys, off].set(
                    v_new.jax.astype(cache["v"].dtype))
            newc = {"k": kc, "v": vc}
            if quant:
                newc["k_scale"] = ks_c
                newc["v_scale"] = vs_c
            if paged_kernel:
                out = paged_attention(
                    q.jax, kc, vc, page_table, pos[:, None],
                    k_scale=ks_c if quant else None,
                    v_scale=vs_c if quant else None,
                    scale=1.0 / (d ** 0.5))
                out = self.out_proj(NDArray(out.reshape(s, 1, h * d)))
                return out, newc
            krow = _paged_rows(kc, page_table)
            vrow = _paged_rows(vc, page_table)
            if quant:
                krow = krow.astype(jnp.float32) * \
                    _paged_rows(ks_c, page_table)
                vrow = vrow.astype(jnp.float32) * \
                    _paged_rows(vs_c, page_table)
            out = _attention_step_slots(q.jax, krow, vrow, pos,
                                        1.0 / (d ** 0.5))
            out = self.out_proj(NDArray(out.reshape(s, 1, h * d)))
            return out, newc
        out = _attention_step_slots(q.jax, krow, vrow, pos,
                                    1.0 / (d ** 0.5))
        out = self.out_proj(NDArray(out.reshape(s, 1, h * d)))
        return out, {"k": kc, "v": vc}

    def forward_step_window(self, x, cache, pos, win_k, win_v, i,
                            page_table=None):
        """READ-ONLY draft decode step (docs/serving.md "Speculative
        decode"): like :meth:`forward_step_slots`, but the new K/V land
        in per-layer WINDOW buffers ``win_k``/``win_v`` (S, W, H, D) at
        column ``i`` instead of the shared cache — the cache is never
        written, so a drafter that is aborted (verify fault, rejected
        proposals, NaN-poisoned draft head) leaves NO trace in shared
        state and degrading to a plain decode step is always safe.

        Row s is drafting token ``i`` of its window: it consumes a
        token at absolute position ``pos[s] + i``, where the cache row
        holds valid K/V for positions ``< pos[s]`` (strictly — the
        consumed token's own K/V lives in window column 0) and window
        columns ``0..i`` hold the speculated positions
        ``pos[s]..pos[s]+i``.  Attention runs over the concatenation
        [cache row (keys < pos), window (cols <= i)].  Returns
        ``(out, new win_k, new win_v)``.  Inference only."""
        import jax.numpy as jnp

        from ..ndarray import NDArray

        s = x.shape[0]
        h, d = self._num_heads, self._head_dim
        q = self.q_proj(x).reshape((s, 1, h, d))
        k_new = self.k_proj(x).reshape((s, h, d))
        v_new = self.v_proj(x).reshape((s, h, d))
        wk = win_k.at[:, i].set(k_new.jax.astype(win_k.dtype))
        wv = win_v.at[:, i].set(v_new.jax.astype(win_v.dtype))
        if page_table is None:
            krow, vrow = cache["k"][:s], cache["v"][:s]
        else:
            krow = _paged_rows(cache["k"], page_table)
            vrow = _paged_rows(cache["v"], page_table)
            if "k_scale" in cache:
                # quantized pages: dequantize the gathered view — the
                # draft stays on the gather arm (it is read-only and
                # off the throughput-critical path), but the window
                # buffers themselves are always fp32 (gpt2.draft_slots)
                krow = krow.astype(jnp.float32) * \
                    _paged_rows(cache["k_scale"], page_table)
                vrow = vrow.astype(jnp.float32) * \
                    _paged_rows(cache["v_scale"], page_table)
        out = _attention_step_window(q.jax, krow, vrow, wk, wv, pos, i,
                                     1.0 / (d ** 0.5))
        out = self.out_proj(NDArray(out.reshape(s, 1, h * d)))
        return out, wk, wv

    def forward_prefill_slots(self, x, cache, slot_idx, offset=None,
                              page_table=None, paged_kernel=False):
        """Bucketed admission prefill: x (B,Tb,U) is a batch of PADDED
        prompts; row i's K/V for positions [0, Tb) land in cache row
        ``slot_idx[i]`` of the persistent (R,Tmax,H,D) cache.  Causal
        attention keeps real tokens blind to the right-padding; padded
        positions write garbage K/V beyond each prompt's true length,
        which decode overwrites (position p is rewritten before it is
        ever attended).  Duplicate slot_idx rows (scratch padding) are
        allowed — last-writer-wins is fine for rows nobody reads.

        CHUNKED/OFFSET variant (``offset`` (B,) int32 given): row i's
        tokens are the chunk at absolute positions ``[offset[i],
        offset[i]+Tb)`` of a prompt whose K/V for ``[0, offset[i])`` is
        ALREADY in cache row ``slot_idx[i]`` (earlier chunks, or a
        prefix-cache copy) — so each chunk query at absolute position p
        attends to the row's cached keys ``<= p``, not just the chunk.
        The chunk K/V are written first, then each row's full cache row
        is gathered back for the attention (the data dependency through
        the scatter keeps XLA honest about ordering).  Writes landing at
        positions >= Tmax (padding columns of a final chunk) are
        out-of-bounds scatters, which jax drops.

        PAGED variant (``page_table`` (S+1, P) int32 given): the cache
        is {'k','v': (N+1, ps, H, D)} pages; row i's K/V scatter through
        ITS table row ``page_table[slot_idx[i]]`` — position p lands in
        page ``table[p//ps]`` at in-page offset ``p%ps``; writes with
        no real target (positions past Tmax, columns spilling into an
        unassigned logical page, the scratch slot-row's padding rows)
        route OUT OF BOUNDS and are dropped — page N is the
        never-written ZERO page unassigned entries read.  The offset
        path gathers each row's pages back into a contiguous
        (B, Tmax, H, D) view so :func:`_attention_chunk` is shared
        verbatim with the dense layout — or, with ``paged_kernel=True``,
        attention reads the pages in place through the table.  A cache
        carrying ``k_scale``/``v_scale`` leaves quantizes the scatter
        write to int8 (scales ride the same routing) and dequantizes at
        attention time, exactly as in :meth:`forward_step_slots`."""
        import jax.numpy as jnp

        from ..ndarray import NDArray
        from ..ops import dot_product_attention
        from ..ops.paged import kv_quantize, paged_attention

        b, t = x.shape[0], x.shape[1]
        h, d = self._num_heads, self._head_dim
        q = self.q_proj(x).reshape((b, t, h, d))
        k = self.k_proj(x).reshape((b, t, h, d))
        v = self.v_proj(x).reshape((b, t, h, d))
        cidx = jnp.arange(t)[None, :] if offset is None \
            else offset[:, None] + jnp.arange(t)[None, :]
        quant = page_table is not None and "k_scale" in cache
        # slot_idx=None means "row i IS slot i" (the speculative verify
        # window, whose batch dim spans every slot): the row read below
        # becomes a SLICE instead of a gather — an identity-permutation
        # gather copies the whole (B, Tmax, H, D) cut per layer, which
        # XLA cannot see through and which would dominate a small
        # verify window's cost
        if page_table is None:
            ridx = jnp.arange(b)[:, None] if slot_idx is None \
                else slot_idx[:, None]
            kc = cache["k"].at[ridx, cidx].set(
                k.jax.astype(cache["k"].dtype))
            vc = cache["v"].at[ridx, cidx].set(
                v.jax.astype(cache["v"].dtype))
        else:
            ps = cache["k"].shape[1]
            tmax = page_table.shape[1] * ps
            zero_page = cache["k"].shape[0] - 1
            trows = page_table[:b] if slot_idx is None \
                else page_table[slot_idx]                    # (B, P)
            lp = jnp.minimum(cidx // ps, page_table.shape[1] - 1)
            mapped = jnp.take_along_axis(trows, lp, axis=1)  # (B, Tb)
            # padding columns past Tmax, columns spilling into a
            # logical page the row never claimed (mixed-offset chunk
            # batches pad every row to the LONGEST take), and the
            # scratch slot-row's padding rows all route OUT OF BOUNDS
            # (dropped) — the zero page must never be written, every
            # live slot reads it through its unassigned table entries
            phys = jnp.where((cidx < tmax) & (mapped != zero_page),
                             mapped, zero_page + 1)
            off = cidx % ps
            if quant:
                kq, ksc = kv_quantize(k.jax)
                vq, vsc = kv_quantize(v.jax)
                kc = cache["k"].at[phys, off].set(kq)
                vc = cache["v"].at[phys, off].set(vq)
                ks_c = cache["k_scale"].at[phys, off].set(ksc)
                vs_c = cache["v_scale"].at[phys, off].set(vsc)
            else:
                kc = cache["k"].at[phys, off].set(
                    k.jax.astype(cache["k"].dtype))
                vc = cache["v"].at[phys, off].set(
                    v.jax.astype(cache["v"].dtype))
        if offset is None:
            # full-prompt prefill attends the chunk's OWN fresh fp32
            # K/V (no cache read) — shared by every layout and dtype
            out = dot_product_attention(q, k, v, causal=True)
        elif page_table is None:
            if slot_idx is None:
                krow, vrow = kc[:b], vc[:b]      # slice, not gather
            else:
                krow = kc[slot_idx]          # (B, Tmax, H, D)
                vrow = vc[slot_idx]
            out = NDArray(_attention_chunk(q.jax, krow, vrow, cidx,
                                           1.0 / (d ** 0.5)))
        else:
            trows = page_table[:b] if slot_idx is None \
                else page_table[slot_idx]
            if paged_kernel:
                out = NDArray(paged_attention(
                    q.jax, kc, vc, trows, cidx,
                    k_scale=ks_c if quant else None,
                    v_scale=vs_c if quant else None,
                    scale=1.0 / (d ** 0.5)))
            else:
                krow = _paged_rows(kc, trows)
                vrow = _paged_rows(vc, trows)
                if quant:
                    krow = krow.astype(jnp.float32) * \
                        _paged_rows(ks_c, trows)
                    vrow = vrow.astype(jnp.float32) * \
                        _paged_rows(vs_c, trows)
                out = NDArray(_attention_chunk(q.jax, krow, vrow, cidx,
                                               1.0 / (d ** 0.5)))
        out = self.out_proj(out.reshape((b, t, h * d)))
        newc = {"k": kc, "v": vc}
        if quant:
            newc["k_scale"] = ks_c
            newc["v_scale"] = vs_c
        return out, newc


def _attention_step(q, k_cache, v_cache, idx, scale):
    """Single-position attention against a KV cache: q (B,1,H,D),
    caches (B,Tmax,H,D), idx = current position (traced int32).  Masked
    to positions <= idx; returns (B,1,H,D)."""
    import jax.numpy as jnp

    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k_cache,
                        preferred_element_type=jnp.float32) * scale
    pos = jnp.arange(k_cache.shape[1])
    logits = jnp.where(pos[None, None, None, :] <= idx, logits, -1e30)
    probs = jnp.exp(logits - jnp.max(logits, axis=-1, keepdims=True))
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v_cache.dtype),
                      v_cache)


def _paged_rows(pages, table_rows):
    """Gather per-slot pages back into contiguous rows: ``pages``
    (N+1, ps, H, D) physical KV pages, ``table_rows`` (B, P) int32 page
    tables → (B, P*ps, H, D), i.e. exactly the dense (B, Tmax, H, D)
    row view, so the masked attentions are shared verbatim between the
    two layouts (token parity by construction: every attended position
    holds identical values, every masked position is selected out
    BEFORE the softmax).  Unassigned logical pages point at the ZERO
    page — pristine zeros, NEVER written (targetless writes route out
    of bounds and drop): that matters because a masked-out lane is
    only harmless if its VALUE is finite — probs underflow to exactly
    0.0 but 0·NaN = NaN in the value einsum, so scratch-page NaN from
    one poisoned row would otherwise fail every live request at once
    (the dense layout isolates rows; paging must too).  The gather
    materializes a (B, Tmax) working set transiently — the HBM win of
    paging is in the PERSISTENT allocation (live tokens, not
    Tmax*slots); :func:`mxnet_tpu.ops.paged.paged_attention` skips the
    materialization entirely (the default ``paged_attention='kernel'``
    arm), keeping this gather as the opt-out reference arm."""
    b, p = table_rows.shape
    g = pages[table_rows]                    # (B, P, ps, H, D)
    return g.reshape(b, p * g.shape[2], g.shape[3], g.shape[4])


def _attention_chunk(q, k_rows, v_rows, qpos, scale):
    """Chunked-prefill attention against populated cache rows: q
    (B,Tq,H,D) are chunk queries at ABSOLUTE positions ``qpos`` (B,Tq);
    k_rows/v_rows (B,Tmax,H,D) are each request's full (gathered) cache
    row, already containing this chunk's K/V plus everything before it.
    Query (b, i) attends keys at positions <= qpos[b, i] — causal over
    the whole prompt, not just the chunk.  This is the decode-step mask
    generalized to Tq queries; O(Tq·Tmax) scores per row, the price of
    offset prefill without a custom kernel (a flash variant with a
    kv-length stop is the TPU follow-up)."""
    import jax.numpy as jnp

    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k_rows,
                        preferred_element_type=jnp.float32) * scale
    keys = jnp.arange(k_rows.shape[1])
    keep = keys[None, None, None, :] <= qpos[:, None, :, None]
    logits = jnp.where(keep, logits, -1e30)
    probs = jnp.exp(logits - jnp.max(logits, axis=-1, keepdims=True))
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v_rows.dtype),
                      v_rows)


def _attention_step_window(q, k_cache, v_cache, k_win, v_win, pos, i,
                           scale):
    """Draft-step attention over [cache row, speculation window]: row s
    attends cache keys at positions ``< pos[s]`` (strictly — unlike
    :func:`_attention_step_slots`'s ``<= pos``, because the draft never
    writes the cache: the consumed token's K/V sits in window column 0)
    plus window columns ``<= i`` (absolute positions
    ``pos[s]..pos[s]+i``).  Same masked-select-before-softmax math as
    every other attention here, so masked lanes only need FINITE
    values, which both sources guarantee."""
    import jax.numpy as jnp

    lc = jnp.einsum("bqhd,bkhd->bhqk", q, k_cache,
                    preferred_element_type=jnp.float32) * scale
    keys = jnp.arange(k_cache.shape[1])
    lc = jnp.where(keys[None, None, None, :] < pos[:, None, None, None],
                   lc, -1e30)
    lw = jnp.einsum("bqhd,bkhd->bhqk", q, k_win,
                    preferred_element_type=jnp.float32) * scale
    cols = jnp.arange(k_win.shape[1])
    lw = jnp.where(cols[None, None, None, :] <= i, lw, -1e30)
    logits = jnp.concatenate([lc, lw], axis=-1)
    probs = jnp.exp(logits - jnp.max(logits, axis=-1, keepdims=True))
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    vals = jnp.concatenate([v_cache, v_win], axis=1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(vals.dtype), vals)


def _attention_step_slots(q, k_cache, v_cache, pos, scale):
    """Per-row-position variant of :func:`_attention_step` for continuous
    batching: row s attends keys <= pos[s] (pos (S,) int32).  Attention
    reads only its own cache row, so slots never contaminate each other."""
    import jax.numpy as jnp

    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k_cache,
                        preferred_element_type=jnp.float32) * scale
    keys = jnp.arange(k_cache.shape[1])
    keep = keys[None, None, None, :] <= pos[:, None, None, None]
    logits = jnp.where(keep, logits, -1e30)
    probs = jnp.exp(logits - jnp.max(logits, axis=-1, keepdims=True))
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v_cache.dtype),
                      v_cache)


class PositionwiseFFN(HybridBlock):
    """Transformer FFN: Dense(hidden) → GELU → Dense(units), hidden sharded
    over ``tp`` (Megatron column then row parallel)."""

    def __init__(self, units, hidden_size, dropout=0.0, activation="gelu",
                 use_bias=True, **kwargs):
        super().__init__(**kwargs)
        self.fc1 = Dense(hidden_size, use_bias=use_bias, flatten=False,
                         in_units=units)
        annotate(self.fc1.weight, "mlp", "embed")
        if self.fc1.bias is not None:
            annotate(self.fc1.bias, "mlp")
        self.act = GELU() if activation == "gelu" else None
        self._activation = activation
        self.fc2 = Dense(units, use_bias=use_bias, flatten=False,
                         in_units=hidden_size)
        annotate(self.fc2.weight, "embed", "mlp")
        self.dropout = Dropout(dropout) if dropout else None

    def forward(self, x):
        from ..ndarray import ops as F
        h = self.fc1(x)
        h = self.act(h) if self.act is not None else \
            F.Activation(h, act_type=self._activation)
        h = self.fc2(h)
        if self.dropout is not None:
            h = self.dropout(h)
        return h


def _block_param_items(block):
    """(structural_name, Parameter) pairs in REGISTRATION order — the
    alignment key for stacking layers.  Structural names ('attn.q_proj.weight')
    are identical across identically-constructed blocks, unlike the global
    per-class name counters ('dense10_weight' sorts before 'dense6_weight')."""
    return list(block._collect_params_with_prefix().items())


def _block_config_key(b):
    """Hyperparameters that change the layer FUNCTION without changing its
    param tree — blocks must agree on all of them to share one scan body."""
    return (
        b.attn._num_heads, b.attn._head_dim, b.attn._causal,
        b.attn._att_dropout,
        b.attn.dropout._rate if b.attn.dropout is not None else 0.0,
        b.ffn._activation,
        b.ffn.dropout._rate if b.ffn.dropout is not None else 0.0,
        b.ln1._axis, b.ln1._eps, b.ln2._axis, b.ln2._eps,
    )


def _scan_eligible(blocks, x) -> bool:
    """True iff the stack can run as ONE lax.scan body: homogeneous layer
    class AND config, params allocated, identical structural param trees
    (names, shapes, dtypes), and we are inside a jit trace (eager mode
    keeps the python loop so the imperative autograd tape sees every op)."""
    import jax

    from ..ndarray import NDArray

    if len(blocks) < 2:
        return False
    cls = type(blocks[0])
    if cls not in (TransformerBlock, TransformerEncoderLayer):
        return False
    if any(type(b) is not cls for b in blocks):
        return False
    try:
        if any(_block_config_key(b) != _block_config_key(blocks[0])
               for b in blocks):
            return False
    except AttributeError:   # subclass with a different structure
        return False
    if not isinstance(x, NDArray) or not isinstance(x.jax, jax.core.Tracer):
        return False
    trees = []
    for b in blocks:
        ps = _block_param_items(b)
        if any(p._data is None for _, p in ps):
            return False
        trees.append(tuple((n, tuple(p.shape), str(p._data.jax.dtype))
                           for n, p in ps))
    return all(t == trees[0] for t in trees)


def _scan_blocks(blocks, x, mask, remat):
    """Run identical transformer layers as ``lax.scan`` over stacked params.

    TPU-first compile economics (SURVEY.md §7.3 hard part 3): a 24-layer
    stack unrolled is 24 copies of the same HLO — XLA compiles the scan
    body ONCE instead.  Gradients flow through the jnp.stack to each
    layer's own Parameter, so checkpoint format / Trainer integration are
    unchanged.  Per-layer RNG (dropout) folds the layer index into the
    ambient trace key so layers decorrelate exactly like the python loop.
    """
    import jax
    import jax.numpy as jnp

    from .. import random as _random
    from ..ndarray import NDArray

    global _scan_engaged_count
    _scan_engaged_count += 1
    b0 = blocks[0]
    b0_params = [p._data for _, p in _block_param_items(b0)]
    per_block = [[p._data.jax for _, p in _block_param_items(blk)]
                 for blk in blocks]
    stacked = [jnp.stack([vals[j] for vals in per_block])
               for j in range(len(b0_params))]
    providers = _random._trace_providers()
    base_key = providers[-1].key if providers else None

    from ..ndarray.ndarray import swap_values

    def body(carry, xs):
        idx, layer_vals = xs[0], xs[1:]
        if base_key is not None:
            _random.push_trace_key(jax.random.fold_in(base_key, idx))
        try:
            with swap_values(b0_params, list(layer_vals)):
                out = b0(NDArray(carry), mask)
            return out.jax, None
        finally:
            if base_key is not None:
                _random.pop_trace_key()

    if remat:
        # remat="dots" keeps matmul outputs resident (cheap: O(layers *
        # tokens * units)) and recomputes only elementwise/softmax in the
        # backward — near-zero extra MXU FLOPs, while full remat (True)
        # recomputes the whole layer.  Without remat a deep scanned stack
        # saves every intermediate per layer and OOMs HBM (BERT-large
        # batch 8 seq 512 wants >16GB of scan-saved activations).
        policy = (jax.checkpoint_policies.checkpoint_dots
                  if remat == "dots" else None)
        body = jax.checkpoint(body, policy=policy)
    idxs = jnp.arange(len(blocks), dtype=jnp.int32)
    h, _ = jax.lax.scan(body, x.jax, (idxs, *stacked))
    return NDArray(h)


# diagnostic: how many times the scan fast path actually compiled in
# (tests assert it engages — a silently ineligible stack would otherwise
# make loop-vs-scan comparisons vacuous)
_scan_engaged_count = 0


def run_blocks(blocks, x, mask=None, scan=None, remat=False):
    """Apply a stack of transformer layers: ``lax.scan`` fast path for deep
    homogeneous stacks under jit (one compiled body), python loop otherwise.

    ``scan=None`` auto-enables scanning at >=8 layers; pass True/False to
    force.  ``remat`` wraps the scan body in jax.checkpoint (activation
    rematerialization for long sequences / deep stacks); ``remat="dots"``
    uses the checkpoint_dots policy (save matmul outputs, recompute only
    elementwise — the usual best memory/FLOP point on TPU).
    """
    use_scan = scan if scan is not None else len(blocks) >= 8
    if use_scan and _scan_eligible(blocks, x):
        return _scan_blocks(blocks, x, mask, remat)
    if remat:
        import jax

        from ..ndarray import NDArray

        if isinstance(x, NDArray) and isinstance(x.jax, jax.core.Tracer):
            # honor remat on the loop path too (short/heterogeneous
            # stacks): checkpoint each layer, folding the layer index
            # into the trace key so fwd and rematerialized traces draw
            # IDENTICAL dropout masks (scan-body key semantics)
            from .. import random as _random
            providers = _random._trace_providers()
            base_key = providers[-1].key if providers else None

            for i, blk in enumerate(blocks):
                def f(h, _blk=blk, _i=i):
                    if base_key is not None:
                        _random.push_trace_key(
                            jax.random.fold_in(base_key, _i))
                    try:
                        return _blk(NDArray(h), mask).jax
                    finally:
                        if base_key is not None:
                            _random.pop_trace_key()
                policy = (jax.checkpoint_policies.checkpoint_dots
                          if remat == "dots" else None)
                x = NDArray(jax.checkpoint(f, policy=policy)(x.jax))
            return x
    for blk in blocks:
        x = blk(x, mask)
    return x


class TransformerBlock(HybridBlock):
    """Pre-LN transformer layer (GPT-2 style)."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 attention_dropout=0.0, causal=True, layer_norm_eps=1e-5,
                 **kwargs):
        super().__init__(**kwargs)
        self.ln1 = LayerNorm(epsilon=layer_norm_eps, in_channels=units)
        self.attn = MultiHeadAttention(
            units, num_heads, dropout=dropout,
            attention_dropout=attention_dropout, causal=causal)
        self.ln2 = LayerNorm(epsilon=layer_norm_eps, in_channels=units)
        self.ffn = PositionwiseFFN(units, hidden_size, dropout=dropout)

    def forward(self, x, mask=None):
        x = x + self.attn(self.ln1(x), mask)
        x = _par.with_sharding_constraint(x, "batch", "seq", None)
        x = x + self.ffn(self.ln2(x))
        return _par.with_sharding_constraint(x, "batch", "seq", None)

    def forward_step(self, x, cache, idx):
        """Incremental decode through the block (see
        MultiHeadAttention.forward_step)."""
        a, cache = self.attn.forward_step(self.ln1(x), cache, idx)
        x = x + a
        x = x + self.ffn(self.ln2(x))
        return x, cache

    def forward_prefill(self, x, cache):
        """Batched cache fill through the block (see
        MultiHeadAttention.forward_prefill)."""
        a, cache = self.attn.forward_prefill(self.ln1(x), cache)
        x = x + a
        x = x + self.ffn(self.ln2(x))
        return x, cache

    def forward_step_slots(self, x, cache, pos, page_table=None,
                           paged_kernel=False):
        """Continuous-batching decode through the block (see
        MultiHeadAttention.forward_step_slots; ``page_table`` selects
        the paged-KV layout, ``paged_kernel`` the in-place Pallas read
        arm)."""
        a, cache = self.attn.forward_step_slots(self.ln1(x), cache, pos,
                                                page_table, paged_kernel)
        x = x + a
        x = x + self.ffn(self.ln2(x))
        return x, cache

    def forward_prefill_slots(self, x, cache, slot_idx, offset=None,
                              page_table=None, paged_kernel=False):
        """Bucketed admission prefill through the block (see
        MultiHeadAttention.forward_prefill_slots; ``offset`` selects the
        chunked/offset variant, ``page_table`` the paged-KV layout,
        ``paged_kernel`` the in-place Pallas read arm)."""
        a, cache = self.attn.forward_prefill_slots(self.ln1(x), cache,
                                                   slot_idx, offset,
                                                   page_table,
                                                   paged_kernel)
        x = x + a
        x = x + self.ffn(self.ln2(x))
        return x, cache

    def forward_step_window(self, x, cache, pos, win_k, win_v, i,
                            page_table=None):
        """Read-only draft decode through the block (see
        MultiHeadAttention.forward_step_window; the cache is never
        written — new K/V ride the window buffers)."""
        a, wk, wv = self.attn.forward_step_window(self.ln1(x), cache,
                                                  pos, win_k, win_v, i,
                                                  page_table)
        x = x + a
        x = x + self.ffn(self.ln2(x))
        return x, wk, wv


class TransformerEncoderLayer(TransformerBlock):
    """Bidirectional (BERT-style) layer: post-LN off, no causal mask."""

    def __init__(self, units, hidden_size, num_heads, **kwargs):
        super().__init__(units, hidden_size, num_heads, causal=False,
                         **kwargs)
