"""``mx.library`` — dynamic extension loading (parity: python/mxnet/
library.py + include/mxnet/lib_api.h, SURVEY.md §2.3 custom-op libraries).

TPU-first: an extension is a Python module (or a C shared library with a
Python shim) that registers ops/partitioners at load time by calling this
framework's registries — the stable-ABI C++ lib_api becomes "import and
register", since compute kernels here are JAX/Pallas functions, not raw
device code.
"""
from __future__ import annotations

import ctypes
import importlib.util
import os
import sys

from . import base as _base

__all__ = ["load", "compiled_with_cxx11_abi"]

_loaded = {}


def load(path, verbose=True):
    """Load an extension library.

    ``.py`` → imported as a module (its top level registers custom ops via
    mx.operator.register / op registries).  ``.so`` → dlopen'd and its
    ``mxnet_tpu_init`` entry point (if present) is called with no args.
    """
    path = os.path.abspath(path)
    if path in _loaded:
        return _loaded[path]
    if not os.path.exists(path):
        raise _base.MXNetError(f"library not found: {path}")
    if path.endswith(".py"):
        name = "mxnet_tpu_ext_" + \
            os.path.splitext(os.path.basename(path))[0]
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules[name] = mod
        spec.loader.exec_module(mod)
        _loaded[path] = mod
        return mod
    if path.endswith(".so") or path.endswith(".dylib"):
        lib = ctypes.CDLL(path, ctypes.RTLD_GLOBAL)
        if hasattr(lib, "mxnet_tpu_init"):
            lib.mxnet_tpu_init()
        _loaded[path] = lib
        return lib
    raise _base.MXNetError(
        f"unsupported extension type: {path} (.py or .so)")


def compiled_with_cxx11_abi():
    return True
