"""``mx.profiler`` — profiling bridge (parity: python/mxnet/profiler.py +
src/profiler/*, SURVEY.md §5.1).

TPU-first: the engine-level Opr timestamping is replaced by XLA/TPU's own
tracing — ``set_state('run')`` starts a ``jax.profiler`` trace whose output
(TensorBoard/perfetto protobuf) carries per-op device timelines with XLA
annotations, strictly more detail than the Chrome-trace the MXNet profiler
emitted.  The mx.profiler API surface (set_config/set_state/dump/Task/
Frame/Marker/pause/resume) is preserved.
"""
from __future__ import annotations

import os
import time
from typing import Optional

from . import base as _base

__all__ = ["set_config", "set_state", "dump", "dumps", "pause", "resume",
           "Task", "Frame", "Marker", "scope", "device_span"]

_config = {
    "filename": "profile.json",
    "profile_all": False,
    "profile_symbolic": True,
    "profile_imperative": True,
    "profile_memory": True,
    "profile_api": True,
    "aggregate_stats": False,
}
_state = {"running": False, "dir": None, "t0": None}


def set_config(**kwargs):
    """Accepts MXNet profiler knobs; `filename` decides the dump directory."""
    _config.update(kwargs)


def set_state(state="stop", profile_process="worker"):
    import jax
    if state == "run" and not _state["running"]:
        logdir = os.path.splitext(_config["filename"])[0] + "_tpu_profile"
        os.makedirs(logdir, exist_ok=True)
        jax.profiler.start_trace(logdir)
        _state.update(running=True, dir=logdir, t0=time.time())
    elif state == "stop" and _state["running"]:
        jax.profiler.stop_trace()
        _state["running"] = False


def pause(profile_process="worker"):
    """MXNet pause ≈ stop collecting; jax traces can't pause, so stop."""
    if _state["running"]:
        set_state("stop")
        _state["paused"] = True


def resume(profile_process="worker"):
    if _state.get("paused"):
        set_state("run")
        _state["paused"] = False


def dump(finished=True, profile_process="worker"):
    """Finish the trace; the perfetto/TensorBoard files land in the logdir
    derived from set_config(filename=...)."""
    if _state["running"]:
        set_state("stop")
    return _state["dir"]


def dumps(reset=False):
    """Aggregate stats summary string (parity: mx.profiler.dumps)."""
    d = _state["dir"]
    if d is None:
        return "(profiler never ran)"
    n = sum(len(files) for _, _, files in os.walk(d))
    return (f"Profile data in {d} ({n} files) — load with TensorBoard "
            f"or ui.perfetto.dev")


class _Annotation:
    """Named range visible in the device trace (parity: profiler.Task/Frame/
    Marker custom ranges; backed by jax.profiler.TraceAnnotation)."""

    def __init__(self, name: str):
        self.name = name
        self._ann = None

    def start(self):
        import jax
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()

    def stop(self):
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            self._ann = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *a):
        self.stop()


class Task(_Annotation):
    pass


class Frame(_Annotation):
    pass


class Marker:
    def __init__(self, name: str):
        self.name = name

    def mark(self, scope_="process", value=None):
        """Instant event in the device trace.  ``value`` (int/float/str)
        is embedded in the annotation name so counters exported by the
        serving layer (queue depth, batch size, shed events) line up
        with the XLA ops around them in the timeline."""
        import jax
        name = f"marker:{self.name}" if value is None else \
            f"marker:{self.name}={value}"
        with jax.profiler.TraceAnnotation(name):
            pass

    def span(self):
        """The same marker as a named RANGE (context manager) — the
        serving scheduler wraps each prefill/decode/forward batch in one
        so per-batch host time is visible next to the device ops it
        launched."""
        return _Annotation(f"marker:{self.name}")


def scope(name: str):
    """Context manager annotating a named range (jax.profiler bridge)."""
    return _Annotation(name)


class _SafeAnnotation(_Annotation):
    """An annotation that degrades to a no-op if jax (or its profiler)
    is unusable — the observability trace bridge must never let a
    device-trace decoration failure break the span it decorates."""

    def start(self):
        try:
            super().start()
        except Exception:
            self._ann = None

    def stop(self):
        try:
            super().stop()
        except Exception:
            self._ann = None


def device_span(name: str) -> _SafeAnnotation:
    """A named range for the jax device trace that NEVER raises — the
    bridge :mod:`mxnet_tpu.observability.trace` uses to land its spans
    inside ``jax.profiler`` captures next to the XLA ops they cover."""
    return _SafeAnnotation(f"span:{name}")
