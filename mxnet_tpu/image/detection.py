"""Detection image pipeline (parity: python/mxnet/image/detection.py —
ImageDetIter + the Det* augmenter family, SURVEY.md §2.5 mx.image row).

Label convention matches upstream: a record's label vector is
``[header_width, obj_width, <header...>, (cls, x1, y1, x2, y2, ...)*N]``
with corner coordinates normalized to [0, 1].  Geometric augmenters
transform image and boxes together; the iterator pads every batch to a
fixed max-objects count (-1-filled rows) so shapes stay static for XLA.
"""
from __future__ import annotations

import random as _pyrandom
from typing import List, Optional

import numpy as onp

from .. import base as _base
from ..io import DataBatch, DataDesc
from ..ndarray import NDArray, array as nd_array
from . import (Augmenter, CreateAugmenter, ImageIter, imdecode, imresize,
               fixed_crop)

__all__ = ["DetAugmenter", "DetBorrowAug", "DetHorizontalFlipAug",
           "DetRandomCropAug", "DetRandomPadAug", "DetRandomSelectAug",
           "CreateDetAugmenter", "ImageDetIter"]


# ------------------------------------------------------------- augmenters

class DetAugmenter:
    """Base detection augmenter: __call__(src, label) -> (src, label)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, src, label):
        return src, label

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__, self._kwargs])


class DetBorrowAug(DetAugmenter):
    """Wrap an image-only augmenter (color jitter, cast, …) — the label
    passes through untouched (parity: detection.py DetBorrowAug)."""

    def __init__(self, augmenter: Augmenter):
        super().__init__(augmenter=augmenter.dumps())
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetHorizontalFlipAug(DetAugmenter):
    """Mirror image AND boxes with probability p."""

    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if _pyrandom.random() < self.p:
            arr = src.asnumpy() if isinstance(src, NDArray) else src
            src = nd_array(onp.ascontiguousarray(arr[:, ::-1]))
            label = label.copy()
            x1 = label[:, 1].copy()
            label[:, 1] = 1.0 - label[:, 3]
            label[:, 3] = 1.0 - x1
        return src, label


class DetRandomCropAug(DetAugmenter):
    """Random crop constrained to keep objects (parity:
    DetRandomCropAug's min_object_covered / area_range contract; boxes are
    clipped to the crop and objects whose center falls outside drop)."""

    def __init__(self, min_object_covered=0.1, aspect_ratio_range=(0.75,
                 1.33), area_range=(0.05, 1.0), max_attempts=50):
        super().__init__(min_object_covered=min_object_covered,
                         aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts)
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts

    def _coverage(self, boxes, x0, y0, x1, y1):
        ix0 = onp.maximum(boxes[:, 1], x0)
        iy0 = onp.maximum(boxes[:, 2], y0)
        ix1 = onp.minimum(boxes[:, 3], x1)
        iy1 = onp.minimum(boxes[:, 4], y1)
        inter = onp.clip(ix1 - ix0, 0, None) * onp.clip(iy1 - iy0, 0, None)
        area = (boxes[:, 3] - boxes[:, 1]) * (boxes[:, 4] - boxes[:, 2])
        return inter / onp.maximum(area, 1e-12)

    def __call__(self, src, label):
        arr = src.asnumpy() if isinstance(src, NDArray) else src
        h, w = arr.shape[:2]
        for _ in range(self.max_attempts):
            area = _pyrandom.uniform(*self.area_range)
            ratio = _pyrandom.uniform(*self.aspect_ratio_range)
            cw = min(1.0, (area * ratio) ** 0.5)
            ch = min(1.0, (area / ratio) ** 0.5)
            x0 = _pyrandom.uniform(0, 1 - cw)
            y0 = _pyrandom.uniform(0, 1 - ch)
            x1, y1 = x0 + cw, y0 + ch
            if label.size:
                cov = self._coverage(label, x0, y0, x1, y1)
                if cov.max(initial=0.0) < self.min_object_covered:
                    continue
                cx = (label[:, 1] + label[:, 3]) / 2
                cy = (label[:, 2] + label[:, 4]) / 2
                keep = (cx > x0) & (cx < x1) & (cy > y0) & (cy < y1)
                if not keep.any():
                    continue
                new = label[keep].copy()
                new[:, 1] = onp.clip((new[:, 1] - x0) / cw, 0, 1)
                new[:, 3] = onp.clip((new[:, 3] - x0) / cw, 0, 1)
                new[:, 2] = onp.clip((new[:, 2] - y0) / ch, 0, 1)
                new[:, 4] = onp.clip((new[:, 4] - y0) / ch, 0, 1)
            else:
                new = label
            px0, py0 = int(x0 * w), int(y0 * h)
            pw, ph = max(1, int(cw * w)), max(1, int(ch * h))
            return fixed_crop(nd_array(arr), px0, py0, pw, ph), new
        return src, label


class DetRandomPadAug(DetAugmenter):
    """Expand the canvas (zoom out) and re-normalize boxes (parity:
    DetRandomPadAug; SSD-style small-object augmentation)."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), max_attempts=50,
                 pad_val=(127, 127, 127)):
        super().__init__(aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts,
                         pad_val=pad_val)
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.pad_val = pad_val
        self.max_attempts = max_attempts

    def __call__(self, src, label):
        arr = src.asnumpy() if isinstance(src, NDArray) else src
        h, w = arr.shape[:2]
        expand = _pyrandom.uniform(*self.area_range)
        if expand <= 1.0:
            return src, label
        ratio = _pyrandom.uniform(*self.aspect_ratio_range)
        nw = min(int(w * (expand * ratio) ** 0.5), int(w * expand))
        nh = min(int(h * (expand / ratio) ** 0.5), int(h * expand))
        nw, nh = max(nw, w), max(nh, h)
        ox = _pyrandom.randint(0, nw - w)
        oy = _pyrandom.randint(0, nh - h)
        canvas = onp.empty((nh, nw, arr.shape[2]), arr.dtype)
        canvas[...] = onp.asarray(self.pad_val, arr.dtype)[:arr.shape[2]]
        canvas[oy:oy + h, ox:ox + w] = arr
        if label.size:
            label = label.copy()
            label[:, 1] = (label[:, 1] * w + ox) / nw
            label[:, 3] = (label[:, 3] * w + ox) / nw
            label[:, 2] = (label[:, 2] * h + oy) / nh
            label[:, 4] = (label[:, 4] * h + oy) / nh
        return nd_array(canvas), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly apply one augmenter from a list (or skip, parity:
    DetRandomSelectAug)."""

    def __init__(self, aug_list: List[DetAugmenter], skip_prob=0.0):
        super().__init__(skip_prob=skip_prob)
        self.aug_list = aug_list
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if _pyrandom.random() < self.skip_prob or not self.aug_list:
            return src, label
        return _pyrandom.choice(self.aug_list)(src, label)


class _DetForceResize(DetAugmenter):
    """Resize to exactly (w, h): normalized boxes are scale-invariant."""

    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src, label):
        return imresize(src, self.size[0], self.size[1],
                        self.interp), label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0, hue=0,
                       pca_noise=0, inter_method=2, min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), max_attempts=50,
                       pad_val=(127, 127, 127)):
    """Standard detection augmenter list (parity: CreateDetAugmenter).

    rand_crop / rand_pad are probabilities of applying the respective
    geometric augmenter.
    """
    auglist: List[DetAugmenter] = []
    if rand_crop > 0:
        crop = DetRandomCropAug(min_object_covered, aspect_ratio_range,
                                (area_range[0], min(1.0, area_range[1])),
                                max_attempts)
        auglist.append(DetRandomSelectAug([crop], 1 - rand_crop))
    if rand_pad > 0:
        pad = DetRandomPadAug(aspect_ratio_range,
                              (1.0, max(1.0, area_range[1])), max_attempts,
                              pad_val)
        auglist.append(DetRandomSelectAug([pad], 1 - rand_pad))
    auglist.append(_DetForceResize((data_shape[2], data_shape[1]),
                                   inter_method))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    # photometric / cast / normalize ride the classification augmenters
    for aug in CreateAugmenter(data_shape, mean=mean, std=std,
                               brightness=brightness, contrast=contrast,
                               saturation=saturation, hue=hue,
                               pca_noise=pca_noise,
                               inter_method=inter_method)[1:]:
        # [0] is the crop/center-crop slot — geometry is handled above
        auglist.append(DetBorrowAug(aug))
    return auglist


# --------------------------------------------------------------- iterator

class ImageDetIter(ImageIter):
    """Detection iterator (parity: mx.image.ImageDetIter): labels are
    variable-length object lists padded to a static (max_objects, 5+)
    tensor per image — -1 class ids mark padding rows (static shapes keep
    the XLA path retrace-free)."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root="", shuffle=False,
                 aug_list=None, imglist=None, **kwargs):
        ik = {k: v for k, v in kwargs.items()
              if k in ("resize", "rand_crop", "rand_pad", "rand_mirror",
                       "mean", "std", "min_object_covered", "area_range",
                       "aspect_ratio_range", "brightness", "contrast",
                       "saturation", "pad_val")}
        if aug_list is None:
            aug_list = CreateDetAugmenter(data_shape, **ik)
        super().__init__(batch_size, data_shape, label_width=1,
                         path_imgrec=path_imgrec, path_imglist=path_imglist,
                         path_root=path_root, shuffle=shuffle,
                         aug_list=[], imglist=imglist)
        self.det_auglist = aug_list
        # normalize stored labels to (N, obj_width) object arrays and find
        # the padded width
        parsed = []
        self._obj_width = 5
        for lab, src, is_raw in self._items:
            objs = self._parse_label(lab)
            self._obj_width = max(self._obj_width, objs.shape[1])
            parsed.append(objs)
        self._max_objects = max((p.shape[0] for p in parsed), default=1)
        self._items = [(p, src, is_raw)
                       for p, (_, src, is_raw) in zip(parsed, self._items)]

    @staticmethod
    def _parse_label(label):
        """[header_width, obj_width, <header...>, objs...] → (N, obj_width)."""
        raw = onp.asarray(label, onp.float32).ravel()
        if raw.size < 2:
            return onp.zeros((0, 5), onp.float32)
        header_width = int(raw[0])
        obj_width = int(raw[1])
        if obj_width < 5 or header_width < 2 or raw.size < header_width:
            raise _base.MXNetError(
                f"malformed detection label (header_width={header_width}, "
                f"obj_width={obj_width}, len={raw.size})")
        body = raw[header_width:]
        n = body.size // obj_width
        return body[:n * obj_width].reshape(n, obj_width).copy()

    @property
    def provide_label(self):
        return [DataDesc("label",
                         (self.batch_size, self._max_objects,
                          self._obj_width))]

    def next(self):
        if self._pos + self.batch_size > len(self._items):
            raise StopIteration
        datas, labels = [], []
        for i in self._order[self._pos:self._pos + self.batch_size]:
            objs, src, is_raw = self._items[i]
            from . import imread
            img = imdecode(src) if is_raw else imread(src)
            label = objs.copy()
            for aug in self.det_auglist:
                img, label = aug(img, label)
            arr = img.asnumpy().astype(onp.float32)
            datas.append(arr.transpose(2, 0, 1))
            pad = onp.full((self._max_objects, self._obj_width), -1.0,
                           onp.float32)
            n = min(label.shape[0], self._max_objects)
            if n:
                pad[:n, :label.shape[1]] = label[:n]
            labels.append(pad)
        self._pos += self.batch_size
        return DataBatch([nd_array(onp.stack(datas))],
                         [nd_array(onp.stack(labels))],
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)
