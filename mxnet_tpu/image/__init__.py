"""``mx.image`` — imperative image utilities (parity: python/mxnet/image/
image.py, SURVEY.md §2.5).  PIL-backed (no OpenCV in the TPU image); outputs
are HWC NDArrays like MXNet's."""
from __future__ import annotations

import io as _io
import os
import random as _pyrandom
from typing import List, Optional

import numpy as onp

from .. import base as _base
from ..io import DataBatch, DataDesc, DataIter
from ..ndarray import NDArray, array as nd_array
from ..utils import colorspace as _colorspace

__all__ = ["imread", "imdecode", "imresize", "resize_short", "fixed_crop",
           "center_crop", "random_crop", "random_size_crop", "color_normalize",
           "HorizontalFlipAug", "RandomCropAug", "CenterCropAug", "ResizeAug",
           "ForceResizeAug", "ColorNormalizeAug", "CastAug",
           "CreateAugmenter", "Augmenter", "ImageIter",
           "BrightnessJitterAug", "ContrastJitterAug", "SaturationJitterAug",
           "HueJitterAug", "ColorJitterAug", "LightingAug", "RandomGrayAug",
           "RandomOrderAug", "imrotate", "copyMakeBorder", "scale_down",
           "parse_lst_line"]


def parse_lst_line(line):
    """Parse one im2rec .lst line 'idx\tlabel...\tpath' →
    (path, label-or-list) or None for malformed lines (single source for
    ImageIter / ImageListDataset / tools)."""
    parts = line.strip().split("\t")
    if len(parts) < 3:
        return None
    labels = [float(x) for x in parts[1:-1]]
    return parts[-1], (labels[0] if len(labels) == 1 else labels)


def _to_pil(img):
    from PIL import Image
    if isinstance(img, NDArray):
        img = img.asnumpy()
    return Image.fromarray(onp.asarray(img).astype(onp.uint8))


def _from_pil(pil) -> NDArray:
    return nd_array(onp.asarray(pil, dtype=onp.uint8))


def imread(filename, flag=1, to_rgb=True) -> NDArray:
    from PIL import Image
    pil = Image.open(filename)
    pil = pil.convert("RGB" if flag else "L")
    arr = onp.asarray(pil)
    if not to_rgb and flag:
        arr = arr[..., ::-1]
    return nd_array(arr)


def imdecode(buf, flag=1, to_rgb=True) -> NDArray:
    from PIL import Image
    if isinstance(buf, NDArray):
        buf = buf.asnumpy().tobytes()
    pil = Image.open(_io.BytesIO(bytes(buf)))
    pil = pil.convert("RGB" if flag else "L")
    arr = onp.asarray(pil)
    if not to_rgb and flag:
        arr = arr[..., ::-1]
    return nd_array(arr)


def imresize(src, w, h, interp=1) -> NDArray:
    from PIL import Image
    interp_map = {0: Image.NEAREST, 1: Image.BILINEAR, 2: Image.BICUBIC,
                  3: Image.NEAREST, 4: Image.LANCZOS}
    pil = _to_pil(src).resize((w, h), interp_map.get(interp, Image.BILINEAR))
    return _from_pil(pil)


def resize_short(src, size, interp=2) -> NDArray:
    h, w = src.shape[:2]
    if h > w:
        new_w, new_h = size, int(h * size / w)
    else:
        new_w, new_h = int(w * size / h), size
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2) -> NDArray:
    arr = src.asnumpy() if isinstance(src, NDArray) else onp.asarray(src)
    out = arr[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        return imresize(out, size[0], size[1], interp)
    return nd_array(out)


def center_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = size
    x0 = max(0, (w - new_w) // 2)
    y0 = max(0, (h - new_h) // 2)
    out = fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size, interp)
    return out, (x0, y0, new_w, new_h)


def random_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = _pyrandom.randint(0, w - new_w)
    y0 = _pyrandom.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def random_size_crop(src, size, area, ratio, interp=2):
    h, w = src.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = _pyrandom.uniform(*area) * src_area
        log_ratio = (onp.log(ratio[0]), onp.log(ratio[1]))
        aspect = onp.exp(_pyrandom.uniform(*log_ratio))
        new_w = int(round((target_area * aspect) ** 0.5))
        new_h = int(round((target_area / aspect) ** 0.5))
        if new_w <= w and new_h <= h:
            x0 = _pyrandom.randint(0, w - new_w)
            y0 = _pyrandom.randint(0, h - new_h)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


def color_normalize(src, mean, std=None) -> NDArray:
    arr = src.asnumpy().astype(onp.float32) \
        if isinstance(src, NDArray) else onp.asarray(src, onp.float32)
    mean = mean.asnumpy() if isinstance(mean, NDArray) else onp.asarray(mean)
    arr = arr - mean
    if std is not None:
        std = std.asnumpy() if isinstance(std, NDArray) else onp.asarray(std)
        arr = arr / std
    return nd_array(arr)


# ------------------------------------------------------------- augmenters

class Augmenter:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, src):
        return src

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__, self._kwargs])


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            arr = src.asnumpy() if isinstance(src, NDArray) else src
            return nd_array(onp.ascontiguousarray(arr[:, ::-1]))
        return src


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean, self.std = mean, std

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(typ=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


def _jitter(src, fn):
    arr = src.asnumpy().astype(onp.float32) \
        if isinstance(src, NDArray) else onp.asarray(src, onp.float32)
    return nd_array(fn(arr))


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + onp.random.uniform(-self.brightness, self.brightness)
        return _jitter(src, lambda a: a * alpha)


class ContrastJitterAug(Augmenter):
    _coef = _colorspace.GRAY_COEF

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + onp.random.uniform(-self.contrast, self.contrast)
        def f(a):
            gray = (a @ self._coef).mean()
            return a * alpha + gray * (1.0 - alpha)
        return _jitter(src, f)


class SaturationJitterAug(Augmenter):
    _coef = _colorspace.GRAY_COEF

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + onp.random.uniform(-self.saturation, self.saturation)
        def f(a):
            gray = (a @ self._coef)[..., None]
            return a * alpha + gray * (1.0 - alpha)
        return _jitter(src, f)


class HueJitterAug(Augmenter):
    _t_yiq = _colorspace.T_YIQ
    _t_rgb = _colorspace.T_RGB

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue

    def __call__(self, src):
        alpha = onp.random.uniform(-self.hue, self.hue) * onp.pi
        u, w = onp.cos(alpha), onp.sin(alpha)
        rot = onp.array([[1, 0, 0], [0, u, -w], [0, w, u]], onp.float32)
        m = self._t_rgb @ rot @ self._t_yiq
        return _jitter(src, lambda a: a @ m.T)


class RandomOrderAug(Augmenter):
    """Apply child augmenters in random order (parity: RandomOrderAug)."""

    def __init__(self, ts):
        super().__init__()
        self.ts = list(ts)

    def dumps(self):
        """Serialize self plus children (upstream RandomOrderAug.dumps)."""
        import json
        return json.dumps([self.__class__.__name__,
                           [json.loads(t.dumps()) for t in self.ts]])

    def __call__(self, src):
        for i in onp.random.permutation(len(self.ts)):
            src = self.ts[i](src)
        return src


class ColorJitterAug(RandomOrderAug):
    """Random-order brightness/contrast/saturation jitter (parity:
    image.ColorJitterAug is a RandomOrderAug upstream too)."""

    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness:
            ts.append(BrightnessJitterAug(brightness))
        if contrast:
            ts.append(ContrastJitterAug(contrast))
        if saturation:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class LightingAug(Augmenter):
    """PCA-based lighting noise (AlexNet-style; parity: image.LightingAug)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd,
                         eigval=onp.asarray(eigval).tolist(),
                         eigvec=onp.asarray(eigvec).tolist())
        self.alphastd = alphastd
        self.eigval = onp.asarray(eigval, onp.float32)
        self.eigvec = onp.asarray(eigvec, onp.float32)

    def __call__(self, src):
        alpha = onp.random.normal(0, self.alphastd, 3).astype(onp.float32)
        rgb = (self.eigvec * alpha * self.eigval).sum(axis=1)
        return _jitter(src, lambda a: a + rgb)


class RandomGrayAug(Augmenter):
    _coef = _colorspace.GRAY_COEF_IMAGE   # upstream image.py matrix

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if onp.random.uniform() < self.p:
            def f(a):
                return onp.repeat((a @ self._coef)[..., None], 3, axis=-1)
            return _jitter(src, f)
        return src


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Build the standard augmenter list (parity: mx.image.CreateAugmenter)."""
    auglist: List[Augmenter] = []
    crop_size = (data_shape[2], data_shape[1])
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    if rand_resize:
        auglist.append(Augmenter())  # placeholder slot, below picks crop
        auglist[-1] = RandomCropAug(crop_size, inter_method)
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        auglist.append(LightingAug(pca_noise,
                                   _colorspace.IMAGENET_PCA_EIGVAL,
                                   _colorspace.IMAGENET_PCA_EIGVEC))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = onp.array([123.68, 116.28, 103.53])
    if std is True:
        std = onp.array([58.395, 57.12, 57.375])
    if mean is not None and std is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


def imrotate(src, rotation_degrees, zoom_in=False, zoom_out=False):
    """Rotate image(s) by the given degrees (parity: image.imrotate —
    upstream contract is CHW / NCHW tensors; HWC also accepted when the
    last dim is 1/3 channels).  Nearest-neighbor sampling, zero fill.
    ``zoom_in`` crops away the black corners, ``zoom_out`` shrinks so the
    whole rotated frame fits (exclusive, like upstream)."""
    if zoom_in and zoom_out:
        raise ValueError("zoom_in and zoom_out are exclusive")
    arr = src.asnumpy() if isinstance(src, NDArray) else onp.asarray(src)
    if arr.ndim == 4:                                   # NCHW
        out = onp.stack([
            imrotate(a, rotation_degrees, zoom_in, zoom_out).asnumpy()
            for a in arr])
        return nd_array(out)
    if arr.ndim == 3 and arr.shape[-1] in (1, 3)             and arr.shape[0] not in (1, 3):
        hwc = arr                                        # HWC
        chw = False
    else:                                                # CHW (upstream)
        hwc = onp.transpose(arr, (1, 2, 0))
        chw = True
    theta = onp.deg2rad(float(rotation_degrees))
    h, w = hwc.shape[:2]
    cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
    scale = abs(onp.cos(theta)) + abs(onp.sin(theta))
    s = 1.0
    if zoom_in:
        s = 1.0 / scale        # sample a smaller source window: no corners
    elif zoom_out:
        s = scale              # sample a larger window: everything fits
    yy, xx = onp.meshgrid(onp.arange(h), onp.arange(w), indexing="ij")
    # inverse rotation mapping (scaled about the center)
    ys = cy + s * ((yy - cy) * onp.cos(theta) - (xx - cx) * onp.sin(theta))
    xs = cx + s * ((yy - cy) * onp.sin(theta) + (xx - cx) * onp.cos(theta))
    yi = onp.round(ys).astype(onp.int64)
    xi = onp.round(xs).astype(onp.int64)
    valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
    out = onp.zeros_like(hwc)
    out[valid] = hwc[yi[valid], xi[valid]]
    if chw:
        out = onp.transpose(out, (2, 0, 1))
    return nd_array(out)


def copyMakeBorder(src, top, bot, left, right, type=0, value=0):  # noqa: A002
    """Pad an HWC image (parity: the cv2-backed mx.image.copyMakeBorder).
    type 0 = constant, 1 = replicate edge; other border types raise."""
    arr = src.asnumpy() if isinstance(src, NDArray) else onp.asarray(src)
    pw = ((top, bot), (left, right)) + ((0, 0),) * (arr.ndim - 2)
    if type == 0:
        out = onp.pad(arr, pw, mode="constant", constant_values=value)
    elif type == 1:
        out = onp.pad(arr, pw, mode="edge")
    else:
        raise NotImplementedError(f"border type {type} not supported")
    return nd_array(out)


def scale_down(src_size, size):
    """Scale (w, h) down to fit within src_size keeping aspect (parity:
    image.scale_down)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


class ImageIter(DataIter):
    """Python-side augmenting image iterator (parity: mx.image.ImageIter):
    reads RecordIO (path_imgrec) or an .lst + image dir (path_imglist)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root="",
                 shuffle=False, aug_list=None, imglist=None, **kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape, **{k: v for k, v in kwargs.items()
                                           if k in ("resize", "rand_crop",
                                                    "rand_mirror", "mean",
                                                    "std")})
        self.shuffle = shuffle
        self._items = []       # (label, payload-or-path, is_raw)
        if path_imgrec:
            from ..recordio import MXRecordIO, unpack
            rec = MXRecordIO(path_imgrec, "r")
            while True:
                r = rec.read()
                if r is None:
                    break
                hdr, payload = unpack(r)
                self._items.append((hdr.label, payload, True))
            rec.close()
        elif path_imglist or imglist is not None:
            rows = []
            if path_imglist:
                with open(path_imglist) as f:
                    for line in f:
                        parts = line.strip().split("\t")
                        rows.append(parts)
            else:
                rows = [[str(i)] + [str(x) for x in r[:-1]] + [r[-1]]
                        for i, r in enumerate(imglist)]
            for parts in rows:
                label = onp.array([float(x) for x in parts[1:-1]],
                                  dtype=onp.float32)
                if label.size == 1:
                    label = float(label[0])
                self._items.append(
                    (label, os.path.join(path_root, parts[-1]), False))
        else:
            raise _base.MXNetError(
                "ImageIter needs path_imgrec, path_imglist or imglist")
        self._order = onp.arange(len(self._items))
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shp = (self.batch_size,) if self.label_width == 1 \
            else (self.batch_size, self.label_width)
        return [DataDesc("softmax_label", shp)]

    def reset(self):
        if self.shuffle:
            onp.random.shuffle(self._order)
        self._pos = 0

    def next(self):
        if self._pos + self.batch_size > len(self._items):
            raise StopIteration
        datas, labels = [], []
        for i in self._order[self._pos:self._pos + self.batch_size]:
            label, src, is_raw = self._items[i]
            img = imdecode(src) if is_raw else imread(src)
            for aug in self.auglist:
                img = aug(img)
            arr = img.asnumpy().astype(onp.float32)
            datas.append(arr.transpose(2, 0, 1))  # HWC → CHW
            labels.append(label)
        self._pos += self.batch_size
        return DataBatch([nd_array(onp.stack(datas))],
                         [nd_array(onp.asarray(labels, onp.float32))])


# detection pipeline (parity: python/mxnet/image/detection.py) — imported
# last so it can reuse the augmenter/iterator machinery above
from .detection import (CreateDetAugmenter, DetAugmenter,  # noqa: E402
                        DetBorrowAug, DetHorizontalFlipAug,
                        DetRandomCropAug, DetRandomPadAug,
                        DetRandomSelectAug, ImageDetIter)
__all__ += ["CreateDetAugmenter", "DetAugmenter", "DetBorrowAug",
            "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
            "DetRandomSelectAug", "ImageDetIter"]
