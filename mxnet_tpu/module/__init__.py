"""``mx.mod`` — the 1.x Module API shim (parity: python/mxnet/module/*,
SURVEY.md §2.6/§3.4).

Kept so GluonCV-era scripts (`mod.fit(train_iter)`) run unmodified.  The
DataParallelExecutorGroup machinery collapses: one Executor evaluates the
symbol through the pure-JAX op registry, and multi-device data parallelism
is the sharded trainer's job (mxnet_tpu.parallel) rather than per-GPU
executor replicas.
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as onp

from .. import base as _base
from .. import initializer as _init_mod
from .. import metric as _metric
from .. import ndarray as nd
from .. import optimizer as _opt
from ..io import DataBatch, DataDesc
from ..ndarray import NDArray

__all__ = ["BaseModule", "Module", "BucketingModule"]


class BaseModule:
    def __init__(self, logger=None):
        self.logger = logger or logging.getLogger()
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False

    # ---- convenience API shared by Module/BucketingModule
    def score(self, eval_data, eval_metric, num_batch=None, reset=True,
              epoch=0):
        if reset:
            eval_data.reset()
        if isinstance(eval_metric, str):
            eval_metric = _metric.create(eval_metric)
        eval_metric.reset()
        for i, batch in enumerate(eval_data):
            if num_batch is not None and i >= num_batch:
                break
            self.forward(batch, is_train=False)
            self.update_metric(eval_metric, batch.label)
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, reset=True):
        if reset:
            eval_data.reset()
        outs = []
        for i, batch in enumerate(eval_data):
            if num_batch is not None and i >= num_batch:
                break
            self.forward(batch, is_train=False)
            o = self.get_outputs()[0]
            if batch.pad:
                o = o[:o.shape[0] - batch.pad]
            outs.append(o.asnumpy())
        return nd.array(onp.concatenate(outs, axis=0))

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None):
        """The classic training loop (parity: BaseModule.fit)."""
        if num_epoch is None:
            raise _base.MXNetError("fit needs num_epoch")
        if initializer is None:
            initializer = _init_mod.Uniform(0.01)
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        if isinstance(eval_metric, str):
            eval_metric = _metric.create(eval_metric)
        validation_metric = validation_metric or eval_metric

        from ..callback import BatchEndParam
        for epoch in range(begin_epoch, num_epoch):
            eval_metric.reset()
            train_data.reset()
            for nbatch, batch in enumerate(train_data):
                self.forward_backward(batch)
                self.update()
                self.update_metric(eval_metric, batch.label)
                if batch_end_callback is not None:
                    params = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                           eval_metric=eval_metric,
                                           locals=locals())
                    for cb in _as_list(batch_end_callback):
                        cb(params)
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            if epoch_end_callback is not None:
                arg_p, aux_p = self.get_params()
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_p, aux_p)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric, epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f",
                                     epoch, name, val)

    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def install_monitor(self, mon):
        """Attach an mx.monitor.Monitor (parity: BaseModule
        .install_monitor → executor set_monitor_callback); the monitor
        observes every eager op output via the dispatcher hook."""
        mon.install()
        self._monitor = mon
        return mon


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


def _topo_nulls(symbol):
    from ..symbol import _topo
    return [n for n in _topo(symbol) if n._op == "null"]


class Module(BaseModule):
    """Single-symbol module (parity: python/mxnet/module/module.py)."""

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=None, context=None,
                 work_load_list=None, fixed_param_names=None,
                 state_names=None, **kwargs):
        super().__init__(logger)
        self._symbol = symbol
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._fixed_param_names = set(fixed_param_names or [])
        args = symbol.list_arguments()
        self._param_names = [a for a in args
                             if a not in self._data_names
                             and a not in self._label_names]
        self._exec = None
        self._arg_params: Dict[str, NDArray] = {}
        self._aux_params: Dict[str, NDArray] = {}

    @property
    def symbol(self):
        return self._symbol

    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        shapes = {d.name: d.shape for d in self._data_shapes}
        shapes.update({d.name: d.shape for d in (self._label_shapes or [])})
        shapes.update({k: tuple(v.shape)
                       for k, v in self._arg_params.items()})
        _, outs, _ = self._symbol.infer_shape(**shapes)
        return list(zip(self.output_names, outs))

    # ------------------------------------------------------------- bind
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, **kwargs):
        if self.binded and not force_rebind:
            return
        norm = lambda ds: [d if isinstance(d, DataDesc) else DataDesc(*d)
                           for d in ds]
        self._data_shapes = norm(data_shapes)
        self._label_shapes = norm(label_shapes) if label_shapes else []
        self._for_training = for_training
        self._inputs_need_grad = inputs_need_grad
        shapes = {d.name: d.shape
                  for d in self._data_shapes + self._label_shapes}
        names = self._symbol.list_arguments()
        aux_names = self._symbol.list_auxiliary_states()
        # explicit Variable(shape=...) attrs participate in shape resolution
        for n in _topo_nulls(self._symbol):
            if "__shape__" in n._attrs:
                shapes.setdefault(n._name, tuple(n._attrs["__shape__"]))
        for k, v in self._arg_params.items():
            shapes.setdefault(k, tuple(v.shape))
        try:
            # partial inference solves layer-parameter shapes (nnvm
            # InferShape parity) — auto-created weights need no explicit
            # shape here
            arg_shapes, _, aux_shapes = self._symbol.infer_shape(**shapes)
        except _base.MXNetError as e:
            raise _base.MXNetError(
                f"Module.bind cannot resolve shapes: {e} — give "
                "sym.Variable(shape=...) explicit shapes, or load params "
                "first (set_params / Module.load)")
        self._arg_shape = dict(zip(names, arg_shapes))
        self._arg_shape.update(dict(zip(aux_names, aux_shapes)))
        args = {}
        grads = {}
        for n in names:
            shape = self._arg_shape[n]
            args[n] = self._arg_params.get(n, nd.zeros(shape))
            if for_training and (n in self._param_names
                                 or (inputs_need_grad
                                     and n in self._data_names)) \
                    and n not in self._fixed_param_names:
                grads[n] = nd.zeros(shape)
        # aux states bind at their declared init (moving_var = ones)
        if aux_names:
            defaults = None
            for n_name in aux_names:
                if n_name not in self._aux_params:
                    if defaults is None:
                        defaults = self._symbol.default_aux_arrays(
                            aux_shapes)
                    self._aux_params[n_name] = defaults[n_name]
                args[n_name] = self._aux_params[n_name]
        req = {n: ("write" if n in grads else "null") for n in args}
        self._exec = self._symbol.bind(args=args, args_grad=grads,
                                       grad_req=req)
        self.binded = True

    # ----------------------------------------------------------- params
    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False,
                    allow_extra=False):
        if self.params_initialized and not force_init:
            return
        initializer = initializer or _init_mod.Uniform(0.01)
        for n in self._param_names:
            if arg_params and n in arg_params:
                arr = arg_params[n]
                arr = arr if isinstance(arr, NDArray) else nd.array(arr)
            elif n in self._arg_params:   # preloaded (Module.load)
                arr = self._arg_params[n]
            else:
                if arg_params and not allow_missing:
                    raise _base.MXNetError(f"missing param {n}")
                arr = nd.zeros(self._arg_shape[n])
                initializer(n, arr)
            self._arg_params[n] = arr
            self._exec.arg_dict[n]._rebind(arr.jax)
        self.params_initialized = True

    def get_params(self):
        return ({k: self._exec.arg_dict[k].copy()
                 for k in self._param_names}, dict(self._aux_params))

    def set_params(self, arg_params, aux_params=None, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(arg_params=arg_params, aux_params=aux_params,
                         allow_missing=allow_missing, force_init=force_init)

    # -------------------------------------------------------- optimizer
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        if self.optimizer_initialized and not force_init:
            return
        if isinstance(optimizer, str):
            params = dict(optimizer_params)
            # upstream Module defaults rescale_grad = 1/batch_size — loss
            # heads (SoftmaxOutput) emit batch-SUMMED gradients
            if "rescale_grad" not in params and self._data_shapes:
                params["rescale_grad"] = 1.0 / self._data_shapes[0].shape[0]
            optimizer = _opt.create(optimizer, **params)
        self._optimizer = optimizer
        self._updater = _opt.get_updater(optimizer)
        self.optimizer_initialized = True

    # ---------------------------------------------------------- compute
    def forward(self, data_batch, is_train=None):
        if is_train is None:
            is_train = self._for_training
        feed = dict(zip(self._data_names, data_batch.data))
        if self._label_names and data_batch.label:
            feed.update(zip(self._label_names, data_batch.label))
        # labels may be absent at inference: bind zeros of the right shape
        for n in self._label_names:
            if n not in feed or n not in self._exec.arg_dict:
                continue
        self._exec.forward(is_train=is_train, **feed)

    def backward(self, out_grads=None):
        self._exec.backward(out_grads)

    def update(self):
        for i, n in enumerate(self._param_names):
            if n in self._fixed_param_names:
                continue
            g = self._exec.grad_dict.get(n)
            if g is None:
                continue
            w = self._exec.arg_dict[n]
            self._updater(i, g, w)

    def get_outputs(self, merge_multi_context=True):
        return self._exec.outputs

    def get_input_grads(self, merge_multi_context=True):
        return [self._exec.grad_dict.get(n) for n in self._data_names]

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        eval_metric.update(labels, self.get_outputs())

    # ------------------------------------------------------- checkpoint
    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        from ..model import save_checkpoint as _save_ckpt
        arg_p, aux_p = self.get_params()
        _save_ckpt(prefix, epoch, self._symbol, arg_p, aux_p)
        if save_optimizer_states:
            with open(f"{prefix}-{epoch:04d}.states", "wb") as f:
                f.write(self._updater.get_states())

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        from ..model import load_checkpoint as _load_ckpt
        symbol, arg_params, aux_params = _load_ckpt(prefix, epoch)
        mod = Module(symbol, **kwargs)
        mod._preloaded = (arg_params, aux_params)
        mod._arg_params = arg_params
        mod._aux_params = aux_params
        if load_optimizer_states:
            mod._preloaded_states = f"{prefix}-{epoch:04d}.states"
        return mod


class BucketingModule(BaseModule):
    """Per-bucket executor cache sharing parameters (parity:
    python/mxnet/module/bucketing_module.py; Sockeye's variable-length
    batching).  Each bucket key jits its own shape — the XLA compile cache
    takes the role of per-bucket bound executors."""

    def __init__(self, sym_gen, default_bucket_key=None, logger=None,
                 context=None, **kwargs):
        super().__init__(logger)
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._buckets: Dict = {}
        self._curr_module: Optional[Module] = None
        self._curr_bucket_key = None
        self._kwargs = kwargs

    @property
    def symbol(self):
        return self._curr_module.symbol

    def _gen_module(self, bucket_key):
        if bucket_key not in self._buckets:
            sym, data_names, label_names = self._sym_gen(bucket_key)
            self._buckets[bucket_key] = Module(
                sym, data_names=data_names, label_names=label_names,
                logger=self.logger)
        return self._buckets[bucket_key]

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, **kwargs):
        mod = self._gen_module(self._default_bucket_key)
        mod.bind(data_shapes, label_shapes, for_training, inputs_need_grad,
                 force_rebind)
        self._curr_module = mod
        self._curr_bucket_key = self._default_bucket_key
        self.binded = True

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        mod = self._gen_module(bucket_key)
        if not mod.binded:
            mod.bind(data_shapes, label_shapes,
                     getattr(self._curr_module, "_for_training", True))
            # share parameters with the master module
            if self._curr_module is not None \
                    and self._curr_module.params_initialized:
                arg_p, aux_p = self._curr_module.get_params()
                shared = {k: v for k, v in arg_p.items()
                          if k in mod._param_names}
                mod.init_params(arg_params=shared, aux_params=aux_p,
                                allow_missing=True, force_init=True)
        self._curr_module = mod
        self._curr_bucket_key = bucket_key

    def init_params(self, *args, **kwargs):
        self._curr_module.init_params(*args, **kwargs)
        self.params_initialized = True

    def init_optimizer(self, *args, **kwargs):
        self._curr_module.init_optimizer(*args, **kwargs)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        key = getattr(data_batch, "bucket_key", self._default_bucket_key)
        if key != self._curr_bucket_key:
            self.switch_bucket(key, data_batch.provide_data,
                               data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)

    def update(self):
        # all buckets share params: push the update through the current one,
        # then propagate values to the others' executors lazily on switch
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def get_params(self):
        return self._curr_module.get_params()

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._curr_module.update_metric(eval_metric, labels)
