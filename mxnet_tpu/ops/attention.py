"""Attention ops: flash attention (Pallas TPU) + XLA reference path.

Capability add over the reference (SURVEY.md §5.7: MXNet has NO flash/ring
attention — its closest machinery is the fused BERT matmuls in
src/operator/contrib/transformer.cc, whose API is kept below for GluonNLP
parity).  The public entry is :func:`dot_product_attention` on NDArrays;
``impl='auto'`` picks the Pallas kernel on TPU for long sequences and the
XLA reference elsewhere.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from .. import base as _base
from .. import random as _random

_NEG_INF = -1e30


# ----------------------------------------------------------------- reference

def _attention_ref(q, k, v, *, causal=False, mask=None, scale=None,
                   dropout=0.0, dropout_key=None):
    """Pure-jax attention; q/k/v are (B, T, H, D).  XLA fuses this well for
    moderate T; the Pallas kernel takes over for long sequences."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        tq, tk = logits.shape[-2], logits.shape[-1]
        idx_q = jnp.arange(tq)[:, None] + (tk - tq)
        idx_k = jnp.arange(tk)[None, :]
        logits = jnp.where(idx_k <= idx_q, logits, _NEG_INF)
    if mask is not None:
        logits = jnp.where(mask, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    if mask is not None or (causal and q.shape[1] > k.shape[1]):
        # fully-masked (degenerate) rows: softmax of an all-_NEG_INF row
        # is a uniform average; zero it instead so this path is
        # bitwise-comparable with the Pallas kernel, which outputs zeros
        # for rows with no matching key (flash.py _finish).  Causal with
        # tq <= tk can never fully mask a row (row i always sees key
        # i + tk - tq), so that common case skips the O(Tq*Tk) scan.
        any_valid = jnp.any(logits > 0.5 * _NEG_INF, axis=-1, keepdims=True)
        probs = jnp.where(any_valid, probs, 0.0)
    if dropout > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout),
                          jnp.zeros_like(probs))
    probs = probs.astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


# ------------------------------------------------------------------ dispatch

def _use_flash(q_shape, causal, mask, dropout, k_shape=None,
               platform=None) -> bool:
    """Flash kernel handles: SELF-attention (tq == tk — cross-attention
    with a different source length falls back to the XLA path), no
    explicit mask, no attention dropout, long 128-aligned sequences,
    head dims the MXU tiles well (64/128/256).  ``platform`` is where the
    op will execute (resolved per-call — a cpu()-context op on a TPU host
    must take the XLA reference path, not compiled Pallas)."""
    if mask is not None or dropout > 0.0:
        return False
    b, t, h, d = q_shape
    if k_shape is not None and tuple(k_shape) != tuple(q_shape):
        return False
    if t < 256 or t % 128 or d not in (64, 128, 256):
        return False
    if (platform or jax.default_backend()) != "tpu":
        return False
    try:
        from . import flash  # noqa: F401
        return True
    except ImportError:
        return False


def flash_attention(q, k, v, *, causal=False, scale=None):
    """Jax-level flash attention entry (Pallas on TPU, reference on CPU)."""
    if _use_flash(q.shape, causal, None, 0.0, k.shape,
                  platform=_base.resolve_exec_platform(q)):
        from .flash import flash_attention as _pallas
        return _pallas(q, k, v, causal=causal, scale=scale)
    return _attention_ref(q, k, v, causal=causal, scale=scale)


def dot_product_attention(query, key, value, *, causal=False, mask=None,
                          segment_ids=None, kv_segment_ids=None,
                          dropout=0.0, scale=None, impl="auto"):
    """NDArray multi-head attention: inputs (B, T, H, D) → (B, T, H, D).

    impl: 'auto' | 'flash' | 'ref'.

    ``segment_ids`` (B, Tq) int enables SEQUENCE PACKING: tokens attend
    only within their own segment (combined with ``causal``/``mask``),
    so multiple short documents share one padded row with zero
    cross-contamination — the standard TPU lever against pad waste.
    ``kv_segment_ids`` (B, Tk) covers cross-attention; it defaults to
    ``segment_ids`` (self-attention).

    Fully-masked rows: a query position whose keys are ALL masked out
    (by ``mask``/``segment_ids``/a degenerate causal shape) returns
    ZEROS, not the historical uniform average over values.  Both impls
    agree on this — the Pallas kernel emits zeros for rows with no
    matching key and the XLA reference path zeroes them to match — so
    padding rows can be sliced away without contaminating reductions.
    """
    from ..ndarray.ops import _as_nd, invoke
    query, key, value = _as_nd(query), _as_nd(key), _as_nd(value)
    nd_in = [query, key, value]
    dkey = None
    if dropout > 0.0 and _base.is_training():
        dkey = _random.next_key(query.context)
    mask_val = mask.jax if hasattr(mask, "jax") else mask
    q_seg = kv_seg = None
    if segment_ids is not None:
        def _seg(x):
            return x.jax if hasattr(x, "jax") else jnp.asarray(x)

        q_seg = _seg(segment_ids)
        kv_seg = _seg(kv_segment_ids) if kv_segment_ids is not None \
            else q_seg
        bq_, tq_ = query.shape[0], query.shape[1]
        tk_ = key.shape[1]
        if tuple(q_seg.shape) != (bq_, tq_) or \
                tuple(kv_seg.shape) != (bq_, tk_):
            raise _base.MXNetError(
                f"segment_ids must be (B, Tq)=({bq_}, {tq_}) and "
                f"kv_segment_ids (B, Tk)=({bq_}, {tk_}); got "
                f"{tuple(q_seg.shape)} / {tuple(kv_seg.shape)} — "
                "cross-attention with Tq != Tk needs an explicit "
                "kv_segment_ids")
    elif kv_segment_ids is not None:
        raise _base.MXNetError("kv_segment_ids requires segment_ids")

    def _full_mask():
        """Segment equality folded into the dense mask — the O(Tq*Tk)
        fallback representation; the Pallas path keeps the raw (B, T) ids
        and masks per-tile in VMEM instead."""
        if q_seg is None:
            return mask_val
        seg_mask = (q_seg[:, None, :, None] ==
                    kv_seg[:, None, None, :])        # (B, 1, Tq, Tk)
        return seg_mask if mask_val is None else \
            jnp.logical_and(mask_val, seg_mask)

    if impl == "flash" and (mask is not None or dropout > 0.0):
        raise _base.MXNetError(
            "impl='flash' does not support an explicit mask or attention "
            "dropout — use impl='auto'/'ref'")

    if impl == "flash" and not _use_flash(query.shape, causal, mask_val,
                                          dropout, key.shape,
                                          platform=_base.resolve_exec_platform(query.jax)):
        raise _base.MXNetError(
            f"impl='flash' requested but the Pallas kernel does not support "
            f"this configuration (shape={tuple(query.shape)}, platform="
            f"{query.jax.devices().pop().platform if hasattr(query.jax, 'devices') else '?'}): "
            "seq_len and head_dim must be multiples of the kernel block "
            "sizes and the device must be a TPU — use impl='auto' to fall "
            "back silently")

    def f(q, k, v):
        if impl != "ref" and _use_flash(q.shape, causal, mask_val, dropout,
                                        k.shape,
                                        platform=_base.resolve_exec_platform(q)):
            from .flash import flash_attention as _pallas
            return _pallas(q, k, v, causal=causal, scale=scale,
                           segment_ids=q_seg,
                           kv_segment_ids=kv_seg)
        return _attention_ref(q, k, v, causal=causal, mask=_full_mask(),
                              scale=scale, dropout=dropout, dropout_key=dkey)

    return invoke("dot_product_attention", f, nd_in)


# GluonNLP-compat fused attention ops live in mxnet_tpu.ndarray.ops
# (parity: src/operator/contrib/transformer.cc); re-exported here so kernel
# users find the whole attention surface in one namespace.
from ..ndarray.ops import (interleaved_matmul_selfatt_qk,  # noqa: E402,F401
                           interleaved_matmul_selfatt_valatt)
