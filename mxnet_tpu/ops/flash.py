"""Pallas TPU flash attention: O(T) memory, MXU-tiled, fwd + custom bwd.

Capability add over the reference (SURVEY.md §5.7: MXNet ships NO
flash/ring attention; its fused BERT matmuls in
src/operator/contrib/transformer.cc materialize the full (T, T) score
matrix).  This kernel never materializes scores: the softmax is computed
online per (block_q, block_k) tile held in VMEM, accumulating into an
f32 VMEM scratch, so long sequences are bounded by HBM for Q/K/V only.

Layout: public entry takes (B, T, H, D) and flattens to (B*H, T, D);
grid = (batch*heads, q_blocks, kv_blocks) with the kv dimension innermost
("arbitrary" semantics — it carries the online-softmax accumulator) and
the first two parallel.  Causal blocks above the diagonal are predicated
out with ``pl.when`` so the MXU never sees them.

The backward pass is the standard flash-attention-2 split: a ``dq``
kernel (grid over q blocks, reducing across kv) and a ``dkv`` kernel
(grid over kv blocks, reducing across q), both re-computing the tile of
probabilities from the saved per-row logsumexp.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed pltpu.TPUCompilerParams -> pltpu.CompilerParams (and will
# eventually drop the old name); accept whichever this jax ships.
_CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    getattr(pltpu, "TPUCompilerParams")

# Measured on TPU v5e (B16 T1024 H12 D64, causal): 128x128 blocks run the
# fwd kernel at 16.7 ms vs 1.6 ms at 1024x1024 — big tiles keep the MXU fed
# (d=64 contractions are half-width already) and amortize grid/DMA overhead.
# 2048x2048 exceeds VMEM (the (bq, bk) f32 score tile alone is 16 MB).
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024
_MASK = -1e30
_LANES = 128

# ~16 MiB VMEM per v4/v5e core; budget leaves headroom for compiler
# temporaries/semaphores so the clamp errs safe rather than tight.
_VMEM_BUDGET = 12 * 2 ** 20


def _vmem_bytes(bq: int, bk: int, d: int, itemsize: int,
                has_seg: bool = False) -> int:
    """Working-set model of one grid step, sized for the WORST of the
    three kernels (the bwd dq/dkv kernels stream four tiles — q, k, v,
    do — where fwd streams three): two live (bq, bk) f32 score-tile
    temporaries (s→p and dp→ds are reused in place), double-buffered
    input tiles, double-buffered output tile(s), and the larger of the
    fwd/dkv f32 accumulator scratch sets.  The segment path adds one
    more (bq, bk)-sized temporary (the q==k equality mask materialized
    by the ``jnp.where``) plus the double-buffered int32 seg-id tiles."""
    score = 2 * 4 * bq * bk
    tiles = 2 * itemsize * d * 2 * (bq + bk)      # dq/dkv stream 4 tiles
    outs = 2 * itemsize * bq * d
    scratch = 4 * max(bq * d + 2 * bq * _LANES,   # fwd: acc + m + l
                      2 * bk * d)                 # dkv: dk_acc + dv_acc
    seg = (4 * bq * bk + 2 * 4 * (bq + bk)) if has_seg else 0
    return score + tiles + outs + scratch + seg


def _clamp_blocks(bq: int, bk: int, d: int, itemsize: int,
                  has_seg: bool = False):
    """Shrink (block_q, block_k) until the working set fits the VMEM
    budget — head-dim/dtype aware, so d=64 bf16 keeps the measured-fast
    1024x1024 while d=256 f32 lands on a safe smaller tile."""
    while _vmem_bytes(bq, bk, d, itemsize, has_seg) > _VMEM_BUDGET and \
            (bq > 128 or bk > 128):
        if bk >= bq and bk > 128:
            bk //= 2
        else:
            bq //= 2
    return bq, bk


def _default_interpret(x) -> bool:
    from ..base import resolve_exec_platform
    return resolve_exec_platform(x) != "tpu"


# --------------------------------------------------------------------- fwd

def _seg_mask(qseg_ref, kseg_ref, s):
    """Mask score tile entries whose q/k tokens belong to different packed
    segments.  The tile-skip predicate lives separately in
    :func:`_run_pred` (shared by all three kernels) so the min/max
    reductions are computed once per grid step."""
    qs = qseg_ref[0, 0, :]                             # (bq,) int32
    ks = kseg_ref[0, 0, :]                             # (bk,) int32
    return jnp.where(qs[:, None] == ks[None, :], s, _MASK)


def _fwd_kernel(*refs, scale, causal, has_seg, block_q, block_k, nk):
    if has_seg:
        (q_ref, k_ref, v_ref, qseg_ref, kseg_ref,
         o_ref, lse_ref, acc_ref, m_ref, l_ref) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = refs
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _MASK)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _tile():
        q = q_ref[0]                                   # (bq, d)
        k = k_ref[0]                                   # (bk, d)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bk)
        if causal:
            row = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            col = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(col <= row, s, _MASK)
        if has_seg:
            s = _seg_mask(qseg_ref, kseg_ref, s)
        m_prev = m_ref[:, :1]                          # (bq, 1)
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_next = jnp.maximum(m_prev, m_cur)
        # masked-safe exp: a tile whose every entry is _MASK for some row
        # (the row's segment starts in a LATER tile) has m_next == _MASK
        # there, and bare exp(s - m_next) would contribute exp(0)=1 per
        # masked entry.  Zero masked entries explicitly instead.
        p = jnp.where(s <= _MASK * 0.5, 0.0, jnp.exp(s - m_next))
        corr = jnp.exp(m_prev - m_next)                # (bq, 1)
        l_next = corr * l_prev + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # (bq, d)
        acc_ref[:] = acc_ref[:] * corr + pv
        m_ref[:] = jnp.broadcast_to(m_next, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_next, l_ref.shape)

    run = _run_pred(causal, has_seg, qi, ki, block_q, block_k,
                    qseg_ref if has_seg else None,
                    kseg_ref if has_seg else None)
    if run is not None:
        @pl.when(run)
        def _():
            _tile()
    else:
        _tile()

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_ref[:, :1]
        # rows with NO matching key anywhere (possible only in degenerate
        # cross-segment cases) get zeros out and a finite lse of _MASK so
        # the backward recompute exp(s - lse) stays 0, never inf
        empty = l <= 0.0
        o_ref[0] = jnp.where(
            empty, 0.0, acc_ref[:] / jnp.where(empty, 1.0, l)
        ).astype(o_ref.dtype)
        lse_ref[0, 0, :] = jnp.where(
            empty[:, 0], _MASK, m_ref[:, 0] + jnp.log(
                jnp.where(empty[:, 0], 1.0, l_ref[:, 0])))


def _seg_specs(nheads, block_q, block_k):
    """BlockSpecs for (B, 1, T) segment-id planes: the grid's flattened
    batch*heads coordinate maps back to the batch row with b // nheads."""
    return [
        pl.BlockSpec((1, 1, block_q),
                     lambda b, i, j: (b // nheads, 0, i)),
        pl.BlockSpec((1, 1, block_k),
                     lambda b, i, j: (b // nheads, 0, j)),
    ]


def _dkv_seg_specs(nheads, block_q, block_k):
    """Same as _seg_specs for the dkv grid, whose (b, j, i) coords carry
    the kv block index second."""
    return [
        pl.BlockSpec((1, 1, block_q),
                     lambda b, j, i: (b // nheads, 0, i)),
        pl.BlockSpec((1, 1, block_k),
                     lambda b, j, i: (b // nheads, 0, j)),
    ]


def _fwd(q, k, v, q_seg, kv_seg, nheads, causal, scale, block_q, block_k,
         interpret):
    bh, tq, d = q.shape
    tk = k.shape[1]
    nq = pl.cdiv(tq, block_q)
    nk = pl.cdiv(tk, block_k)
    has_seg = q_seg is not None
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, has_seg=has_seg,
        block_q=block_q, block_k=block_k, nk=nk)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
    ]
    args = [q, k, v]
    if has_seg:
        in_specs += _seg_specs(nheads, block_q, block_k)
        args += [q_seg, kv_seg]
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            # lse is (bh, 1, tq) so each qi owns its own (1, 1, block_q)
            # tile — TPU block rules demand last-two dims divisible by
            # (8, 128) or equal to the array dims, and a shared full-row
            # block would race across megacore's parallel qi partitions.
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, tq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=4 * bh * tq * tk * d, transcendentals=bh * tq * tk,
            bytes_accessed=2 * (q.size + k.size + v.size) * q.dtype.itemsize),
        interpret=interpret,
    )(*args)
    return out, lse


# --------------------------------------------------------------------- bwd

def _run_pred(causal, has_seg, qi, ki, block_q, block_k,
              qseg_ref, kseg_ref):
    """Tile-skip predicate shared by all three kernels: the causal
    above-diagonal test plus a range-disjointness test on the tile's
    segment ids — exact for the packed layout (ids non-decreasing along
    the row) and conservative (never skips a tile that could match) for
    arbitrary ids."""
    run = None
    if causal:
        run = ki * block_k < (qi + 1) * block_q
    if has_seg:
        qs = qseg_ref[0, 0, :]
        ks = kseg_ref[0, 0, :]
        overlap = jnp.logical_and(jnp.min(ks) <= jnp.max(qs),
                                  jnp.max(ks) >= jnp.min(qs))
        run = overlap if run is None else jnp.logical_and(run, overlap)
    return run


def _dq_kernel(*refs, scale, causal, has_seg, block_q, block_k, nk):
    if has_seg:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         qseg_ref, kseg_ref, dq_ref, acc_ref) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dq_ref, acc_ref) = refs
        qseg_ref = kseg_ref = None
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _tile():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            row = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            col = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(col <= row, s, _MASK)
        if has_seg:
            s = _seg_mask(qseg_ref, kseg_ref, s)
        lse = lse_ref[0, 0, :]
        delta = delta_ref[0, 0, :]
        p = jnp.where(s <= _MASK * 0.5, 0.0, jnp.exp(s - lse[:, None]))
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # (bq, bk)
        ds = p * (dp - delta[:, None]) * scale
        acc_ref[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    run = _run_pred(causal, has_seg, qi, ki, block_q, block_k,
                        qseg_ref, kseg_ref)
    if run is not None:
        @pl.when(run)
        def _():
            _tile()
    else:
        _tile()

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _dkv_kernel(*refs, scale, causal, has_seg, block_q, block_k, nq):
    if has_seg:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         qseg_ref, kseg_ref, dk_ref, dv_ref, dk_acc, dv_acc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
        qseg_ref = kseg_ref = None
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _tile():
        q = q_ref[0]                                   # (bq, d)
        k = k_ref[0]                                   # (bk, d)
        do = do_ref[0]                                 # (bq, d)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bk)
        if causal:
            row = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            col = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(col <= row, s, _MASK)
        if has_seg:
            s = _seg_mask(qseg_ref, kseg_ref, s)
        lse = lse_ref[0, 0, :]
        delta = delta_ref[0, 0, :]
        p = jnp.where(s <= _MASK * 0.5, 0.0, jnp.exp(s - lse[:, None]))
        # dV += P^T @ dO
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # (bk, d)
        dp = jax.lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # (bq, bk)
        ds = p * (dp - delta[:, None]) * scale
        # dK += dS^T @ Q
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    run = _run_pred(causal, has_seg, qi, ki, block_q, block_k,
                        qseg_ref, kseg_ref)
    if run is not None:
        @pl.when(run)
        def _():
            _tile()
    else:
        _tile()

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_impl(q, k, v, q_seg, kv_seg, out, lse, do, nheads, causal, scale,
              block_q, block_k, interpret):
    bh, tq, d = q.shape
    tk = k.shape[1]
    nq = pl.cdiv(tq, block_q)
    nk = pl.cdiv(tk, block_k)
    has_seg = q_seg is not None
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)[:, None, :]               # (bh, 1, tq)

    dq_in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
        pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
    ]
    args = [q, k, v, do, lse, delta]
    if has_seg:
        dq_in_specs += _seg_specs(nheads, block_q, block_k)
        args += [q_seg, kv_seg]
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          has_seg=has_seg,
                          block_q=block_q, block_k=block_k, nk=nk),
        grid=(bh, nq, nk),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*args)

    dkv_in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
        pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, 0, i)),
        pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, 0, i)),
    ]
    if has_seg:
        dkv_in_specs += _dkv_seg_specs(nheads, block_q, block_k)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          has_seg=has_seg,
                          block_q=block_q, block_k=block_k, nq=nq),
        grid=(bh, nk, nq),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, tk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*args)
    return dq, dk, dv


# ----------------------------------------------------------- custom_vjp glue
# Segment ids travel as primal args (they are data, not static config) and
# return symbolic-zero cotangents of dtype float0, the JAX contract for
# integer primal inputs.

def _int_zero_cotangent(x):
    if x is None:
        return None
    import numpy as _np

    from jax import dtypes as _dtypes
    return _np.zeros(x.shape, _dtypes.float0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash(q, k, v, q_seg, kv_seg, nheads, causal, scale, block_q, block_k,
           interpret):
    out, _ = _fwd(q, k, v, q_seg, kv_seg, nheads, causal, scale,
                  block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, q_seg, kv_seg, nheads, causal, scale, block_q,
               block_k, interpret):
    out, lse = _fwd(q, k, v, q_seg, kv_seg, nheads, causal, scale,
                    block_q, block_k, interpret)
    return out, (q, k, v, q_seg, kv_seg, out, lse)


def _flash_bwd(nheads, causal, scale, block_q, block_k, interpret, res, do):
    q, k, v, q_seg, kv_seg, out, lse = res
    dq, dk, dv = _bwd_impl(q, k, v, q_seg, kv_seg, out, lse, do, nheads,
                           causal, scale, block_q, block_k, interpret)
    return (dq, dk, dv,
            _int_zero_cotangent(q_seg), _int_zero_cotangent(kv_seg))


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, causal: bool = False,
                    scale: Optional[float] = None,
                    segment_ids=None, kv_segment_ids=None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: Optional[bool] = None):
    """Flash attention on (B, T, H, D) inputs → (B, T, H, D).

    T must be a multiple of the block sizes and D one of 64/128/256 (the
    dispatcher in :mod:`mxnet_tpu.ops.attention` guarantees this before
    routing here).  ``interpret`` defaults to True off-TPU so the same
    kernel is unit-testable on the CPU backend.

    ``segment_ids`` (B, Tq) int enables SEQUENCE PACKING in-kernel:
    tokens attend only within their own segment; tiles whose q/k segment
    ranges cannot overlap are skipped at block level (exact skip for the
    packed non-decreasing layout), so packed long-context training keeps
    the O(T) memory AND the sub-quadratic compute of the kernel.
    ``kv_segment_ids`` defaults to ``segment_ids``.  Degenerate rows with
    no matching key anywhere output zeros — as does the XLA reference
    path (``attention.py:_attention_ref`` zeroes fully-masked rows), so
    the two paths are comparable row-for-row.
    """
    b, tq, h, d = q.shape
    tk = k.shape[1]
    if causal and tq != tk:
        raise ValueError("causal flash attention requires tq == tk "
                         f"(got {tq} vs {tk})")
    scale = float(scale) if scale is not None else 1.0 / (d ** 0.5)
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)
    block_q, block_k = _clamp_blocks(block_q, block_k, d,
                                     jnp.dtype(q.dtype).itemsize,
                                     has_seg=segment_ids is not None)
    # halve until the block divides the sequence (any T that is a multiple
    # of 128 lands on a legal block by 128 at the latest)
    while block_q > 128 and tq % block_q:
        block_q //= 2
    while block_k > 128 and tk % block_k:
        block_k //= 2
    if tq % block_q or tk % block_k:
        raise ValueError(
            f"seq lens ({tq}, {tk}) must divide by blocks "
            f"({block_q}, {block_k})")
    if interpret is None:
        interpret = _default_interpret(q)

    q_seg = kv_seg = None
    if segment_ids is not None:
        q_seg = jnp.asarray(segment_ids, jnp.int32)[:, None, :]  # (B,1,Tq)
        kv_seg = (jnp.asarray(kv_segment_ids, jnp.int32)[:, None, :]
                  if kv_segment_ids is not None else q_seg)
        if q_seg.shape != (b, 1, tq) or kv_seg.shape != (b, 1, tk):
            raise ValueError(
                f"segment_ids must be (B, Tq)=({b}, {tq}) / "
                f"(B, Tk)=({b}, {tk}); got {segment_ids.shape}"
                + (f" / {kv_segment_ids.shape}"
                   if kv_segment_ids is not None else ""))
    elif kv_segment_ids is not None:
        raise ValueError("kv_segment_ids requires segment_ids")

    def flat(x, t):
        return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)

    out = _flash(flat(q, tq), flat(k, tk), flat(v, tk), q_seg, kv_seg,
                 h, causal, scale, block_q, block_k, bool(interpret))
    return out.reshape(b, h, tq, d).transpose(0, 2, 1, 3)
