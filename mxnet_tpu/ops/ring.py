"""Ring attention: sequence/context parallelism over the ``sp`` mesh axis.

Capability add over the reference (SURVEY.md §5.7: MXNet has no sequence
parallelism of any kind).  Q stays resident; K/V chunks rotate around the
ring of ``sp`` devices via ``jax.lax.ppermute`` (XLA lowers this to ICI
neighbor RDMA), and partial attention results merge with the numerically
stable online-softmax rule — so a sequence of length T costs each device
O(T/sp) memory and the compute of its own chunk, while the compiler
overlaps each step's ppermute with the previous step's matmuls.

Each per-chunk block is wrapped in ``jax.checkpoint`` so the backward pass
recomputes the (Tl x Tl) score tiles instead of keeping ``sp`` of them
alive, matching flash attention's memory discipline.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .attention import _NEG_INF as _MASK


@functools.partial(jax.checkpoint, static_argnums=(5, 6))
def _block(q, k, v, q_pos, kv_pos, causal, scale):
    """Partial attention of local Q against one K/V chunk.

    q: (B, Tl, H, D); k/v: (B, Tc, H, D); returns un-normalized
    (pv (B, H, Tl, D) f32, m (B, H, Tl, 1), l (B, H, Tl, 1)).
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        keep = kv_pos[None, :] <= q_pos[:, None]       # (Tl, Tc)
        s = jnp.where(keep[None, None], s, _MASK)
    m = jnp.max(s, axis=-1, keepdims=True)             # (B, H, Tl, 1)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    pv = jnp.einsum("bhqk,bkhd->bhqd", p.astype(v.dtype), v,
                    preferred_element_type=jnp.float32)
    return pv, m, l


def _ring_local(q, k, v, *, axis, steps, causal, scale):
    """Per-device body under shard_map: q/k/v are local (B, Tl, H, D)."""
    idx = jax.lax.axis_index(axis)
    tl = q.shape[1]
    offs = jax.lax.broadcasted_iota(jnp.int32, (tl, 1), 0)[:, 0]
    q_pos = idx * tl + offs
    perm = [(i, (i + 1) % steps) for i in range(steps)]

    acc = m = l = None
    for t in range(steps):
        owner = (idx - t) % steps                      # chunk's home device
        kv_pos = owner * tl + offs
        pv, m_c, l_c = _block(q, k, v, q_pos, kv_pos, causal, scale)
        if t == 0:
            # step 0 is the diagonal chunk: every causal row has >= 1
            # unmasked key, so m is finite and later fully-masked chunks
            # (m_c = _MASK) merge with weight exp(_MASK - m) = 0, nan-free
            acc, m, l = pv, m_c, l_c
        else:
            m_new = jnp.maximum(m, m_c)
            c_old = jnp.exp(m - m_new)
            c_new = jnp.exp(m_c - m_new)
            acc = acc * c_old + pv * c_new
            l = l * c_old + l_c * c_new
            m = m_new
        if t + 1 < steps:
            k = jax.lax.ppermute(k, axis, perm)
            v = jax.lax.ppermute(v, axis, perm)
    out = acc / l                                      # (B, H, Tl, D)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ring_attention(q, k, v, *, causal: bool = False,
                   scale: Optional[float] = None, mesh=None,
                   axis: str = "sp", batch_axis: str = "dp",
                   heads_axis: str = "tp"):
    """Sequence-parallel attention on global (B, T, H, D) jax arrays.

    Shards T over ``axis`` (and B over ``batch_axis``, H over
    ``heads_axis``) with shard_map; falls back to single-device attention
    when the axis has size 1.  Requires T divisible by the axis size.
    """
    from ..parallel.mesh import axis_size, current_mesh
    mesh = mesh or current_mesh()
    steps = axis_size(mesh, axis) if mesh is not None else 1
    d = q.shape[-1]
    scale = float(scale) if scale is not None else 1.0 / (d ** 0.5)
    if steps == 1:
        from .attention import flash_attention
        return flash_attention(q, k, v, causal=causal, scale=scale)
    t = q.shape[1]
    if t % steps or k.shape[1] != t:
        raise ValueError(
            f"ring attention needs tq == tk divisible by |{axis}|={steps}, "
            f"got tq={t}, tk={k.shape[1]}")
    spec = P(batch_axis, axis, heads_axis, None)
    body = functools.partial(_ring_local, axis=axis, steps=steps,
                             causal=causal, scale=scale)
    from ._smap import shard_mapped_qkv
    return shard_mapped_qkv(body, mesh, spec, q, k, v)


def nd_ring_attention(query, key, value, *, causal=False, scale=None,
                      mesh=None, axis="sp"):
    """NDArray-level entry (autograd-recorded) for ring attention."""
    from ..ndarray.ops import _as_nd, invoke
    query, key, value = _as_nd(query), _as_nd(key), _as_nd(value)

    def f(q, k, v):
        return ring_attention(q, k, v, causal=causal, scale=scale,
                              mesh=mesh, axis=axis)

    return invoke("ring_attention", f, [query, key, value])
