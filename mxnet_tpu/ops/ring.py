"""Ring attention: sequence/context parallelism over the ``sp`` mesh axis.

Capability add over the reference (SURVEY.md §5.7: MXNet has no sequence
parallelism of any kind).  Q stays resident; K/V chunks rotate around the
ring of ``sp`` devices via ``jax.lax.ppermute`` (XLA lowers this to ICI
neighbor RDMA), and partial attention results merge with the numerically
stable online-softmax rule — so a sequence of length T costs each device
O(T/sp) memory and the compute of its own chunk, while the compiler
overlaps each step's ppermute with the previous step's matmuls.

Each per-chunk block is wrapped in ``jax.checkpoint`` so the backward pass
recomputes the (Tl x Tl) score tiles instead of keeping ``sp`` of them
alive, matching flash attention's memory discipline.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .attention import _NEG_INF as _MASK


@functools.partial(jax.checkpoint, static_argnums=(7, 8))
def _block(q, k, v, q_pos, kv_pos, q_seg, kv_seg, causal, scale):
    """Partial attention of local Q against one K/V chunk.

    q: (B, Tl, H, D); k/v: (B, Tc, H, D); optional q_seg (B, Tl) /
    kv_seg (B, Tc) packed segment ids mask cross-segment pairs; returns
    un-normalized (pv (B, H, Tl, D) f32, m (B, H, Tl, 1),
    l (B, H, Tl, 1)).  A fully-masked row yields m = _MASK and l = 0,
    which merges with zero weight — nan-free as long as SOME chunk
    (the diagonal: self-key always matches) is live for the row.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        keep = kv_pos[None, :] <= q_pos[:, None]       # (Tl, Tc)
        s = jnp.where(keep[None, None], s, _MASK)
    if q_seg is not None:
        keep_seg = q_seg[:, None, :, None] == kv_seg[:, None, None, :]
        s = jnp.where(keep_seg, s, _MASK)              # (B, 1, Tl, Tc)
    m = jnp.max(s, axis=-1, keepdims=True)             # (B, H, Tl, 1)
    # zero fully-masked entries (not exp(_MASK - _MASK) = 1) so packed
    # rows whose segment lives in another chunk contribute l = 0 here
    p = jnp.where(s <= _MASK * 0.5, 0.0, jnp.exp(s - m))
    l = jnp.sum(p, axis=-1, keepdims=True)
    pv = jnp.einsum("bhqk,bkhd->bhqd", p.astype(v.dtype), v,
                    preferred_element_type=jnp.float32)
    return pv, m, l


def _merge(state, pv, m_c, l_c):
    """Online-softmax combination of two partial attention results."""
    acc, m, l = state
    m_new = jnp.maximum(m, m_c)
    c_old = jnp.exp(m - m_new)
    c_new = jnp.exp(m_c - m_new)
    return acc * c_old + pv * c_new, m_new, l * c_old + l_c * c_new


def _ring_local(q, k, v, seg=None, *, axis, steps, causal, scale):
    """Per-device body under shard_map: q/k/v are local (B, Tl, H, D);
    ``seg`` (B, Tl) local packed segment ids — the kv-side ids rotate
    around the ring with their K/V chunk."""
    idx = jax.lax.axis_index(axis)
    tl = q.shape[1]
    offs = jax.lax.broadcasted_iota(jnp.int32, (tl, 1), 0)[:, 0]
    q_pos = idx * tl + offs
    perm = [(i, (i + 1) % steps) for i in range(steps)]
    kv_seg = seg

    acc = m = l = None
    for t in range(steps):
        owner = (idx - t) % steps                      # chunk's home device
        kv_pos = owner * tl + offs
        pv, m_c, l_c = _block(q, k, v, q_pos, kv_pos, seg, kv_seg,
                              causal, scale)
        if t == 0:
            # step 0 is the diagonal chunk: every row has >= 1 unmasked
            # key (its own — causal keeps the diagonal, segments always
            # self-match), so m is finite and later fully-masked chunks
            # (m_c = _MASK) merge with weight exp(_MASK - m) = 0, nan-free
            acc, m, l = pv, m_c, l_c
        else:
            acc, m, l = _merge((acc, m, l), pv, m_c, l_c)
        if t + 1 < steps:
            k = jax.lax.ppermute(k, axis, perm)
            v = jax.lax.ppermute(v, axis, perm)
            if kv_seg is not None:
                kv_seg = jax.lax.ppermute(kv_seg, axis, perm)
    out = acc / l                                      # (B, H, Tl, D)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def _ring_local_balanced(q, k, v, seg=None, *, axis, steps, scale):
    """Zigzag-balanced CAUSAL ring body: each device's local rows are the
    pair [chunk idx | chunk 2*steps-1-idx] of a 2*steps-way split, so at
    every ring step every device computes exactly two UNMASKED
    half-blocks (plus the two causal diagonals at step 0) — half the
    FLOPs of masking a full block per step, with uniform load.

    ``seg`` (B, Tl) rides the SAME zigzag layout as q/k/v (the caller
    permutes it); its kv-side halves rotate with their K/V chunk, and
    the half-block "fully live" structure is unchanged — segment masking
    only ever REMOVES pairs inside a block, so every _block below passes
    its half-ids and the step-0 self-key guarantee keeps rows nan-free."""
    idx = jax.lax.axis_index(axis)
    tl = q.shape[1]
    hl = tl // 2
    offs = jax.lax.broadcasted_iota(jnp.int32, (hl, 1), 0)[:, 0]
    perm = [(i, (i + 1) % steps) for i in range(steps)]

    def halves(x):
        return x[:, :hl], x[:, hl:]

    q_lo, q_hi = halves(q)
    k_lo, k_hi = halves(k)
    v_lo, v_hi = halves(v)
    s_lo = s_hi = None
    if seg is not None:
        s_lo, s_hi = halves(seg)

    # step 0 (own chunks): high-vs-low is FULLY live (chunk 2s-1-i > i);
    # the two diagonals are the only blocks that ever need a causal mask
    lo = _block(q_lo, k_lo, v_lo, offs, offs, s_lo, s_lo, True, scale)
    hi = _block(q_hi, k_lo, v_lo, offs, offs, s_hi, s_lo, False, scale)
    hi = _merge(hi, *_block(q_hi, k_hi, v_hi, offs, offs, s_hi, s_hi,
                            True, scale))

    kk, vv, ss = k, v, seg
    for t in range(1, steps):
        kk = jax.lax.ppermute(kk, axis, perm)
        vv = jax.lax.ppermute(vv, axis, perm)
        ko_lo, ko_hi = halves(kk)
        vo_lo, vo_hi = halves(vv)
        so_lo = so_hi = None
        if ss is not None:
            ss = jax.lax.ppermute(ss, axis, perm)
            so_lo, so_hi = halves(ss)
        # always live: local HIGH rows vs arriving LOW chunk (no mask:
        # every high-chunk position exceeds every low-chunk position)
        hi = _merge(hi, *_block(q_hi, ko_lo, vo_lo, offs, offs,
                                s_hi, so_lo, False, scale))
        # exactly one of (lo vs lo) / (hi vs hi) is live, fully unmasked:
        # owner o = (idx - t) mod steps; o <= idx  <=>  idx >= t
        pred = idx >= t
        q_s = jnp.where(pred, q_lo, q_hi)
        k_s = jnp.where(pred, ko_lo, ko_hi)
        v_s = jnp.where(pred, vo_lo, vo_hi)
        qs_seg = ks_seg = None
        if ss is not None:
            qs_seg = jnp.where(pred, s_lo, s_hi)
            ks_seg = jnp.where(pred, so_lo, so_hi)
        pv, m_c, l_c = _block(q_s, k_s, v_s, offs, offs, qs_seg, ks_seg,
                              False, scale)
        lo_new = _merge(lo, pv, m_c, l_c)
        hi_new = _merge(hi, pv, m_c, l_c)
        lo = tuple(jnp.where(pred, n, o) for n, o in zip(lo_new, lo))
        hi = tuple(jnp.where(pred, o, n) for n, o in zip(hi_new, hi))
    out_lo = (lo[0] / lo[2]).transpose(0, 2, 1, 3)
    out_hi = (hi[0] / hi[2]).transpose(0, 2, 1, 3)
    return jnp.concatenate([out_lo, out_hi], axis=1).astype(q.dtype)


def _zigzag_perm(t: int, steps: int):
    """new-position -> old-position index map laying the sequence out as
    device i = [chunk i | chunk 2*steps-1-i] of a 2*steps-way split."""
    import numpy as onp
    hl = t // (2 * steps)
    order = []
    for i in range(steps):
        order.append(onp.arange(i * hl, (i + 1) * hl))
        j = 2 * steps - 1 - i
        order.append(onp.arange(j * hl, (j + 1) * hl))
    return onp.concatenate(order)


def ring_attention(q, k, v, *, causal: bool = False,
                   scale: Optional[float] = None, mesh=None,
                   axis: str = "sp", batch_axis: str = "dp",
                   heads_axis: str = "tp", balance: Optional[bool] = None,
                   segment_ids=None):
    """Sequence-parallel attention on global (B, T, H, D) jax arrays.

    Shards T over ``axis`` (and B over ``batch_axis``, H over
    ``heads_axis``) with shard_map; falls back to single-device attention
    when the axis has size 1.  Requires T divisible by the axis size.

    ``balance`` (default: on for causal when shapes allow) uses the
    zigzag layout — each device holds an early and a late half-chunk, so
    causal masking never throws away half of every computed block: 2x
    fewer attention FLOPs at uniform per-device load, for one static
    gather of the inputs and one of the output.

    ``segment_ids`` (B, T) int enables sequence packing: tokens attend
    only within their own segment.  The ids shard over (batch, seq) and
    the kv-side plane rotates around the ring with its K/V chunk; on the
    balanced path the ids ride the same zigzag permutation as q/k/v, so
    callers always pass them in the NATURAL sequence order.
    """
    from ..parallel.mesh import axis_size, current_mesh
    mesh = mesh or current_mesh()
    steps = axis_size(mesh, axis) if mesh is not None else 1
    d = q.shape[-1]
    scale = float(scale) if scale is not None else 1.0 / (d ** 0.5)
    if segment_ids is not None:
        segment_ids = jnp.asarray(segment_ids)
        if tuple(segment_ids.shape) != (q.shape[0], q.shape[1]):
            raise ValueError(
                f"segment_ids must be (B, T)={(q.shape[0], q.shape[1])}, "
                f"got {tuple(segment_ids.shape)}")
    if steps == 1:
        from .. import base as _base
        from .attention import _attention_ref, _use_flash, flash_attention
        if segment_ids is None:
            return flash_attention(q, k, v, causal=causal, scale=scale)
        if _use_flash(q.shape, causal, None, 0.0, k.shape,
                      platform=_base.resolve_exec_platform(q)):
            # the Pallas kernel masks per-tile from the raw (B, T) ids —
            # never materialize the dense (B, 1, T, T) mask on TPU
            from .flash import flash_attention as _pallas
            return _pallas(q, k, v, causal=causal, scale=scale,
                           segment_ids=segment_ids,
                           kv_segment_ids=segment_ids)
        seg_mask = (segment_ids[:, None, :, None] ==
                    segment_ids[:, None, None, :])
        return _attention_ref(q, k, v, causal=causal, mask=seg_mask,
                              scale=scale)
    t = q.shape[1]
    if t % steps or k.shape[1] != t:
        raise ValueError(
            f"ring attention needs tq == tk divisible by |{axis}|={steps}, "
            f"got tq={t}, tk={k.shape[1]}")
    spec = P(batch_axis, axis, heads_axis, None)
    seg_spec = P(batch_axis, axis)
    from ._smap import shard_mapped_qkv
    if balance and not causal:
        raise ValueError("balance=True requires causal=True (the zigzag "
                         "layout only pays off under causal masking)")
    if balance is None:
        balance = causal and t % (2 * steps) == 0
    if causal and balance:
        if t % (2 * steps):
            raise ValueError(
                f"balanced causal ring needs T divisible by "
                f"2*|{axis}|={2 * steps}, got {t}")
        perm = jnp.asarray(_zigzag_perm(t, steps))
        inv = jnp.argsort(perm)
        qz, kz, vz = (jnp.take(x, perm, axis=1) for x in (q, k, v))
        body = functools.partial(_ring_local_balanced, axis=axis,
                                 steps=steps, scale=scale)
        if segment_ids is not None:
            segz = jnp.take(segment_ids, perm, axis=1)
            out = shard_mapped_qkv(body, mesh, spec, qz, kz, vz, segz,
                                   extra_specs=(seg_spec,))
        else:
            out = shard_mapped_qkv(body, mesh, spec, qz, kz, vz)
        return jnp.take(out, inv, axis=1)
    body = functools.partial(_ring_local, axis=axis, steps=steps,
                             causal=causal, scale=scale)
    if segment_ids is not None:
        return shard_mapped_qkv(body, mesh, spec, q, k, v, segment_ids,
                                extra_specs=(seg_spec,))
    return shard_mapped_qkv(body, mesh, spec, q, k, v)


def nd_ring_attention(query, key, value, *, causal=False, scale=None,
                      mesh=None, axis="sp", balance=None, segment_ids=None):
    """NDArray-level entry (autograd-recorded) for ring attention.
    ``segment_ids`` (B, T) is a non-differentiable side input."""
    from ..ndarray.ops import _as_nd, invoke
    query, key, value = _as_nd(query), _as_nd(key), _as_nd(value)
    seg = segment_ids.jax if hasattr(segment_ids, "jax") else segment_ids

    def f(q, k, v):
        return ring_attention(q, k, v, causal=causal, scale=scale,
                              mesh=mesh, axis=axis, balance=balance,
                              segment_ids=seg)

    return invoke("ring_attention", f, [query, key, value])
