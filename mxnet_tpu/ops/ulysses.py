"""Ulysses (all-to-all) sequence parallelism over the ``sp`` mesh axis.

Capability add over the reference (SURVEY.md §5.7: MXNet has no sequence
parallelism; the survey names ring attention AND all-to-all
sequence/context parallelism as the two first-class long-context
strategies).  DeepSpeed-Ulysses recipe: inputs arrive sharded over the
sequence dim; one ``all_to_all`` re-shards them over the HEAD dim (each
device receives the FULL sequence for H/sp of the heads), attention runs
locally — through the Pallas flash kernel on TPU, so the O(T) online-
softmax memory discipline is preserved at full sequence length — and a
second ``all_to_all`` restores sequence sharding.

Trade-off vs the ring (ops/ring.py): 2 all-to-alls of the whole
activation per attention instead of ``sp`` neighbor ppermutes of K/V;
better when heads are plentiful and ICI all-to-all bandwidth is high,
worse at very long T where K/V chunks are much smaller than Q·out.  Both
ride ICI; selection is ``seq_parallel='ring'|'ulysses'`` on the model or
``MXNET_TPU_SEQ_PARALLEL`` (docs/env_vars.md).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
from jax.sharding import PartitionSpec as P

__all__ = ["ulysses_attention", "nd_ulysses_attention"]


def _ulysses_local(q, k, v, *, axis, causal, scale):
    """Per-device body under shard_map: q/k/v local (B, T/sp, H, D)."""
    # seq-shard -> head-shard: every device gets the full sequence for
    # its H/sp head group
    q, k, v = (jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                  tiled=True) for x in (q, k, v))
    from .attention import flash_attention
    out = flash_attention(q, k, v, causal=causal, scale=scale)
    # head-shard -> seq-shard
    return jax.lax.all_to_all(out, axis, split_axis=1, concat_axis=2,
                              tiled=True)


def ulysses_attention(q, k, v, *, causal: bool = False,
                      scale: Optional[float] = None, mesh=None,
                      axis: str = "sp", batch_axis: str = "dp",
                      heads_axis: str = "tp"):
    """Sequence-parallel attention on global (B, T, H, D) jax arrays via
    head/sequence all-to-all re-sharding.  Requires T and the LOCAL head
    count (H / |heads_axis|) divisible by |axis|."""
    from ..parallel.mesh import axis_size, current_mesh
    mesh = mesh or current_mesh()
    sp = axis_size(mesh, axis) if mesh is not None else 1
    d = q.shape[-1]
    scale = float(scale) if scale is not None else 1.0 / (d ** 0.5)
    if sp == 1:
        from .attention import flash_attention
        return flash_attention(q, k, v, causal=causal, scale=scale)
    t, h = q.shape[1], q.shape[2]
    tp = axis_size(mesh, heads_axis)
    if t % sp or k.shape[1] != t:
        raise ValueError(
            f"ulysses attention needs tq == tk divisible by |{axis}|={sp},"
            f" got tq={t}, tk={k.shape[1]}")
    if h % tp or (h // tp) % sp:
        raise ValueError(
            f"ulysses attention needs heads {h} divisible by "
            f"|{heads_axis}|={tp} and local heads {h}//{tp} divisible by "
            f"|{axis}|={sp}")
    spec = P(batch_axis, axis, heads_axis, None)
    body = functools.partial(_ulysses_local, axis=axis, causal=causal,
                             scale=scale)
    from ._smap import shard_mapped_qkv
    return shard_mapped_qkv(body, mesh, spec, q, k, v)


def nd_ulysses_attention(query, key, value, *, causal=False, scale=None,
                         mesh=None, axis="sp"):
    """NDArray-level entry (autograd-recorded) for Ulysses attention."""
    from ..ndarray.ops import _as_nd, invoke
    query, key, value = _as_nd(query), _as_nd(key), _as_nd(value)

    def f(q, k, v):
        return ulysses_attention(q, k, v, causal=causal, scale=scale,
                                 mesh=mesh, axis=axis)

    return invoke("ulysses_attention", f, [query, key, value])
