"""mxnet_tpu.ops — kernel-level operations (Pallas TPU kernels + jax
reference paths).

This is the TPU analogue of MXNet's hand-written kernel layer
(src/operator/contrib/transformer.cc, fused CUDA ops): most of the op
surface lives in mxnet_tpu.ndarray.ops as straight jax/lax code that XLA
compiles optimally; this package holds the few ops where a hand-written
Pallas kernel beats the compiler (flash attention) plus their pure-XLA
reference implementations used for testing and CPU execution.
"""
from .attention import (dot_product_attention, flash_attention,
                        interleaved_matmul_selfatt_qk,
                        interleaved_matmul_selfatt_valatt)
from .paged import kv_dequantize, kv_quantize, paged_attention
from .ring import nd_ring_attention, ring_attention
from .ulysses import nd_ulysses_attention, ulysses_attention

__all__ = ["dot_product_attention", "flash_attention",
           "interleaved_matmul_selfatt_qk",
           "interleaved_matmul_selfatt_valatt",
           "kv_dequantize", "kv_quantize", "paged_attention",
           "nd_ring_attention", "ring_attention",
           "nd_ulysses_attention", "ulysses_attention"]
