"""Pallas TPU paged attention: reads K/V pages in place through the
page table, with int8 dequantization fused into the tile loads.

Capability add over PR 11's paged layout (docs/serving.md "Paged KV
cache"): the original paged forward gathered every slot's pages into a
dense ``(B, Tmax, H, D)`` row (``models/transformer.py:_paged_rows``)
before attending — a full re-densification of the KV working set per
layer per step.  This kernel never materializes that row: the grid's
innermost dimension walks the page table itself, the BlockSpec index
map turns each ``table[slot, j]`` entry into the DMA source block, and
the online softmax (same structure as :mod:`.flash`) accumulates across
pages in VMEM scratch.  Pages past a slot's maximum query position are
predicated out with ``pl.when`` — a decode step over a 4-page prompt in
a 64-page-table engine touches 4 page tiles of compute, not 64 dense
rows.

Quantized pages (``kv_quant='int8'``) ride the same grid: the int8
page tile and its ``(ps, H, 1)`` fp32 scale tile stream together and
the dequantize (``tile.astype(f32) * scale``) fuses into the load, so
quantization halves-of-halves the HBM traffic without a separate
dequant pass.  The unassigned-slot zero page (pool ``scratch``) reads
as zeros under any scale — masked lanes stay finite, the engine's
NaN-guard contract (docs/resilience.md) is untouched.

Interpret-mode fallback mirrors :mod:`.flash`: off-TPU the kernel runs
under the Pallas interpreter, so the CPU test suite exercises the SAME
kernel body that TPU compiles.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed pltpu.TPUCompilerParams -> pltpu.CompilerParams (and will
# eventually drop the old name); accept whichever this jax ships.
_CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    getattr(pltpu, "TPUCompilerParams")

_MASK = -1e30
_LANES = 128

__all__ = ["paged_attention", "kv_quantize", "kv_dequantize"]


def _default_interpret(x) -> bool:
    from ..base import resolve_exec_platform
    return resolve_exec_platform(x) != "tpu"


# ------------------------------------------------------------ quantization

def kv_quantize(x, scale_dtype=jnp.float32):
    """Symmetric per-position-per-head int8 quantization of a K/V
    activation: ``scale = max(|x|, axis=-1) / 127`` over the head_dim
    lanes, ``q = round(x / scale)``.  Returns ``(int8 values, scale)``
    with ``scale`` shaped like ``x`` but with a trailing dim of 1, so
    it scatters/gathers/shards exactly like a cache leaf.

    The scale floor keeps all-zero inputs (padding rows, the zero page)
    exactly representable: ``q = 0, scale = tiny`` dequantizes to 0.0,
    never 0/0."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -127.0, 127.0)
    return q.astype(jnp.int8), scale.astype(scale_dtype)


def kv_dequantize(q, scale):
    """Inverse of :func:`kv_quantize` for the XLA (non-kernel) paths:
    broadcast-multiply the int8 values by their per-(position, head)
    scale.  Used by the dense-row gather arm and the draft window."""
    return q.astype(jnp.float32) * scale.astype(jnp.float32)


# ----------------------------------------------------------------- kernel

def _paged_kernel(table_ref, qmax_ref, *refs, scale, ps, nheads, npages,
                  quant):
    """One (slot, page) grid step.  ``table_ref``/``qmax_ref`` are the
    scalar-prefetched page table row block and per-slot max query
    position; page/scale tiles arrive already DMA'd by the index maps
    below.  The online softmax is flash.py's, with heads unrolled in
    Python: each head's (Tq, ps) score tile is tiny, and unrolling
    keeps every dot a plain 2D MXU contraction."""
    if quant:
        (q_ref, pos_ref, k_ref, v_ref, ks_ref, vs_ref,
         o_ref, acc_ref, m_ref, l_ref) = refs
    else:
        q_ref, pos_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref = refs
    s_id = pl.program_id(0)
    j = pl.program_id(1)
    tq = q_ref.shape[1]

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _MASK)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _tile():
        kf = k_ref[0].astype(jnp.float32)              # (ps, H, D)
        vf = v_ref[0].astype(jnp.float32)
        if quant:
            # dequant fused into the tile load: the int8 page and its
            # (ps, H, 1) scale stream together, nothing re-densifies
            kf = kf * ks_ref[0]
            vf = vf * vs_ref[0]
        qpos = pos_ref[0, 0, :]                        # (Tq,) int32
        keys = j * ps + jax.lax.broadcasted_iota(
            jnp.int32, (tq, ps), 1)                    # (Tq, ps)
        keep = keys <= qpos[:, None]
        qf = q_ref[0].astype(jnp.float32)              # (Tq, H, D)
        for h in range(nheads):
            s = jax.lax.dot_general(
                qf[:, h, :], kf[:, h, :], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale  # (Tq, ps)
            s = jnp.where(keep, s, _MASK)
            m_prev = m_ref[h][:, :1]                   # (Tq, 1)
            l_prev = l_ref[h][:, :1]
            m_cur = jnp.max(s, axis=1, keepdims=True)
            m_next = jnp.maximum(m_prev, m_cur)
            # masked-safe exp (flash.py): a page fully beyond some
            # row's qpos has m_next == _MASK there, and bare
            # exp(s - m_next) would add exp(0)=1 per masked lane
            p = jnp.where(s <= _MASK * 0.5, 0.0, jnp.exp(s - m_next))
            corr = jnp.exp(m_prev - m_next)            # (Tq, 1)
            l_next = corr * l_prev + jnp.sum(p, axis=1, keepdims=True)
            pv = jax.lax.dot_general(
                p, vf[:, h, :], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)    # (Tq, D)
            acc_ref[h] = acc_ref[h] * corr + pv
            m_ref[h] = jnp.broadcast_to(m_next, m_ref.shape[1:])
            l_ref[h] = jnp.broadcast_to(l_next, l_ref.shape[1:])

    # page-skip predicate — the win over the dense gather: a page whose
    # FIRST key already exceeds the slot's max query position is fully
    # masked, so its tile never touches the MXU
    @pl.when(j * ps <= qmax_ref[s_id])
    def _():
        _tile()

    @pl.when(j == npages - 1)
    def _finish():
        out = []
        for h in range(nheads):
            l = l_ref[h][:, :1]
            # same degenerate-row guard as flash: zeros out, never inf
            empty = l <= 0.0
            out.append(jnp.where(
                empty, 0.0, acc_ref[h] / jnp.where(empty, 1.0, l)))
        o_ref[0] = jnp.stack(out, axis=1).astype(o_ref.dtype)


def paged_attention(q, k_pages, v_pages, table_rows, qpos, *,
                    k_scale=None, v_scale=None,
                    scale: Optional[float] = None,
                    interpret: Optional[bool] = None):
    """Attention over paged K/V, read in place through the page table.

    Args:
      q: ``(B, Tq, H, D)`` queries — ``Tq=1`` for decode, the chunk
        width for chunked prefill / the spec-decode verify window.
      k_pages, v_pages: ``(N+1, ps, H, D)`` per-layer page arrays
        (float, or int8 when quantized); the LAST page is the engine's
        never-written zero page.
      table_rows: ``(B, P)`` int32 — each slot's page-table row;
        unassigned entries point at the zero page.
      qpos: ``(B, Tq)`` int32 absolute query positions; key position
        ``k`` is attended iff ``k <= qpos`` (inclusive causal mask,
        matching ``_attention_chunk``/``_attention_step_slots``).
      k_scale, v_scale: ``(N+1, ps, H, 1)`` fp32 per-position-per-head
        scales — required iff the pages are int8.

    Returns ``(B, Tq, H, D)`` in ``q``'s dtype.  The output for rows
    whose table maps entirely to the zero page (parked slots) is
    finite garbage, exactly like the gather arm — callers discard it.
    """
    b, tq, h, d = q.shape
    npages_total, ps = k_pages.shape[0], k_pages.shape[1]
    p = table_rows.shape[1]
    quant = jnp.issubdtype(k_pages.dtype, jnp.integer)
    if quant and (k_scale is None or v_scale is None):
        raise ValueError("int8 pages require k_scale/v_scale")
    scale = float(scale) if scale is not None else 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = _default_interpret(q)

    table_rows = table_rows.astype(jnp.int32)
    qpos = jnp.asarray(qpos, jnp.int32)
    qmax = jnp.max(qpos, axis=1)                       # (B,)
    pos3 = qpos[:, None, :]                            # (B, 1, Tq)

    kernel = functools.partial(
        _paged_kernel, scale=scale, ps=ps, nheads=h, npages=p,
        quant=bool(quant))
    page_spec = pl.BlockSpec(
        (1, ps, h, d), lambda s, j, tbl, qm: (tbl[s, j], 0, 0, 0))
    in_specs = [
        pl.BlockSpec((1, tq, h, d), lambda s, j, tbl, qm: (s, 0, 0, 0)),
        pl.BlockSpec((1, 1, tq), lambda s, j, tbl, qm: (s, 0, 0)),
        page_spec,
        page_spec,
    ]
    args = [q, pos3, k_pages, v_pages]
    if quant:
        scale_spec = pl.BlockSpec(
            (1, ps, h, 1), lambda s, j, tbl, qm: (tbl[s, j], 0, 0, 0))
        in_specs += [scale_spec, scale_spec]
        args += [k_scale.astype(jnp.float32), v_scale.astype(jnp.float32)]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, p),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, tq, h, d), lambda s, j, tbl, qm: (s, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, tq, d), jnp.float32),
            pltpu.VMEM((h, tq, _LANES), jnp.float32),
            pltpu.VMEM((h, tq, _LANES), jnp.float32),
        ],
    )
    itemsize = jnp.dtype(k_pages.dtype).itemsize
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, tq, h, d), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=4 * b * tq * p * ps * h * d,
            transcendentals=b * tq * p * ps * h,
            bytes_accessed=(2 * b * p * ps * h * d * itemsize
                            + 2 * q.size * q.dtype.itemsize)),
        interpret=bool(interpret),
    )(table_rows, qmax, *args)
    return out
