"""Shared eager-entry scaffold for shard_map'd attention ops (ring,
ulysses): spread single-device arrays over the mesh, run the mapped
body, and restore the caller's placement so downstream eager math sees
a consistent device."""
from __future__ import annotations

import jax

__all__ = ["shard_mapped_qkv"]


def _shard_map(body, mesh, in_specs, out_specs):
    """jax.shard_map moved twice across jax versions: top-level with
    check_vma (new), top-level with check_rep, experimental with
    check_rep (0.4.x) — probe in that order."""
    if hasattr(jax, "shard_map"):
        try:
            return jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False)
        except TypeError:
            return jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=False)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def shard_mapped_qkv(body, mesh, spec, q, k, v, *extra, extra_specs=()):
    """Run ``body(q, k, v, *extra)`` under shard_map.  ``extra`` carries
    side inputs with their own partition specs (e.g. packed segment-id
    planes, sharded over batch+sequence only)."""
    if len(extra) != len(extra_specs):
        raise ValueError(
            f"shard_mapped_qkv: {len(extra)} extra inputs but "
            f"{len(extra_specs)} extra_specs — each side input needs "
            "exactly one partition spec")
    restore = None
    if not isinstance(q, jax.core.Tracer):
        from jax.sharding import NamedSharding
        sh = NamedSharding(mesh, spec)
        if q.sharding != sh:
            restore = q.sharding
        q, k, v = (jax.device_put(x, sh) for x in (q, k, v))
        extra = tuple(jax.device_put(x, NamedSharding(mesh, s))
                      for x, s in zip(extra, extra_specs))
    f = _shard_map(body, mesh, (spec, spec, spec, *extra_specs), spec)
    out = f(q, k, v, *extra)
    if restore is not None:
        out = jax.device_put(out, restore)
    return out
