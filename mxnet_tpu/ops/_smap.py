"""Shared eager-entry scaffold for shard_map'd attention ops (ring,
ulysses): spread single-device arrays over the mesh, run the mapped
body, and restore the caller's placement so downstream eager math sees
a consistent device."""
from __future__ import annotations

import jax

__all__ = ["shard_mapped_qkv"]


def shard_mapped_qkv(body, mesh, spec, q, k, v, *extra, extra_specs=()):
    """Run ``body(q, k, v, *extra)`` under shard_map.  ``extra`` carries
    side inputs with their own partition specs (e.g. packed segment-id
    planes, sharded over batch+sequence only)."""
    restore = None
    if not isinstance(q, jax.core.Tracer):
        from jax.sharding import NamedSharding
        sh = NamedSharding(mesh, spec)
        if q.sharding != sh:
            restore = q.sharding
        q, k, v = (jax.device_put(x, sh) for x in (q, k, v))
        extra = tuple(jax.device_put(x, NamedSharding(mesh, s))
                      for x, s in zip(extra, extra_specs))
    f = jax.shard_map(body, mesh=mesh,
                      in_specs=(spec, spec, spec, *extra_specs),
                      out_specs=spec, check_vma=False)
    out = f(q, k, v, *extra)
    if restore is not None:
        out = jax.device_put(out, restore)
    return out
