"""Test helpers (parity: python/mxnet/test_utils.py — assert_almost_equal,
check_numeric_gradient, rand_ndarray, with_seed)."""
from __future__ import annotations

import functools
import random as pyrandom

import numpy as onp

from . import random as _random
from .ndarray import NDArray, array

__all__ = ["default_rtol", "default_atol", "assert_almost_equal",
           "rand_ndarray", "rand_shape_nd", "check_numeric_gradient",
           "with_seed", "same"]


def default_rtol(dtype=onp.float32):
    return {onp.dtype(onp.float16): 1e-2, onp.dtype(onp.float32): 1e-4,
            onp.dtype(onp.float64): 1e-6}.get(onp.dtype(dtype), 1e-4)


def default_atol(dtype=onp.float32):
    return {onp.dtype(onp.float16): 1e-2, onp.dtype(onp.float32): 1e-5,
            onp.dtype(onp.float64): 1e-7}.get(onp.dtype(dtype), 1e-5)


def _np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return onp.asarray(x)


def same(a, b):
    return onp.array_equal(_np(a), _np(b))


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b")):
    a, b = _np(a), _np(b)
    rtol = rtol if rtol is not None else default_rtol(a.dtype)
    atol = atol if atol is not None else default_atol(a.dtype)
    onp.testing.assert_allclose(a, b, rtol=rtol, atol=atol,
                                err_msg=f"{names[0]} vs {names[1]}")


def rand_shape_nd(ndim, dim=10):
    return tuple(onp.random.randint(1, dim + 1, size=ndim))


def rand_ndarray(shape, dtype="float32", ctx=None, scale=1.0):
    return array(onp.random.uniform(-scale, scale, size=shape)
                 .astype(dtype), ctx=ctx)


def check_numeric_gradient(fn, inputs, eps=1e-3, rtol=1e-2, atol=1e-3):
    """Finite-difference gradient check of `fn` (NDArray-in, scalar
    NDArray-out) against the autograd tape."""
    from . import autograd
    inputs = [x if isinstance(x, NDArray) else array(x) for x in inputs]
    for x in inputs:
        x.attach_grad()
    with autograd.record():
        out = fn(*inputs)
    out.backward()
    analytic = [x.grad.asnumpy().copy() for x in inputs]

    for k, x in enumerate(inputs):
        base = x.asnumpy().astype(onp.float64)
        num_grad = onp.zeros_like(base)
        flat = base.reshape(-1)
        ng = num_grad.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + eps
            with autograd.pause():
                fp = float(fn(*[array(base.astype(onp.float32))
                                if j == k else inputs[j]
                                for j in range(len(inputs))]).asscalar())
            flat[i] = orig - eps
            with autograd.pause():
                fm = float(fn(*[array(base.astype(onp.float32))
                                if j == k else inputs[j]
                                for j in range(len(inputs))]).asscalar())
            flat[i] = orig
            ng[i] = (fp - fm) / (2 * eps)
        onp.testing.assert_allclose(analytic[k], num_grad, rtol=rtol,
                                    atol=atol,
                                    err_msg=f"gradient of input {k}")


def with_seed(seed=None):
    """Decorator seeding python/numpy/framework RNGs per test (parity:
    tests/python/unittest/common.py)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            s = seed if seed is not None else onp.random.randint(0, 2**31)
            pyrandom.seed(s)
            onp.random.seed(s)
            _random.seed(s)
            try:
                return fn(*args, **kwargs)
            except Exception:
                print(f"Test failed with seed {s}")
                raise
        return wrapper

    return deco
