"""Test helpers (parity: python/mxnet/test_utils.py — assert_almost_equal,
check_numeric_gradient, rand_ndarray, with_seed)."""
from __future__ import annotations

import functools
import random as pyrandom

import numpy as onp

from . import random as _random
from .ndarray import NDArray, array

__all__ = ["default_rtol", "default_atol", "assert_almost_equal",
           "rand_ndarray", "rand_shape_nd", "check_numeric_gradient",
           "with_seed", "same", "check_consistency", "default_context",
           "set_default_context", "list_gpus", "download", "get_mnist",
           "get_mnist_iterator", "mesh_devices"]


def mesh_devices(n):
    """First ``n`` XLA devices, or ``None`` when the process has fewer.

    Multi-device CPU runs (sharded-serving / sharded-trainer tests,
    docs/serving.md "Sharded decode") need
    ``XLA_FLAGS=--xla_force_host_platform_device_count=N`` set BEFORE
    jax initializes — tests/conftest.py does this via
    :func:`~mxnet_tpu.utils.platform.force_cpu`.  This helper GUARDS
    instead of re-forcing: the flag is read exactly once at backend
    bring-up, so forcing it from inside a test would either be a no-op
    or poison the already-initialized platform for the rest of the
    process.  Callers (the ``mesh_devices`` pytest fixture, the bench
    workloads) skip or degrade when ``None`` comes back."""
    import jax

    devs = jax.devices()
    return list(devs[:int(n)]) if len(devs) >= int(n) else None


def _as_dtype(dtype):
    """np.dtype that also understands 'bfloat16' (via ml_dtypes)."""
    if str(dtype) == "bfloat16":
        import ml_dtypes
        return onp.dtype(ml_dtypes.bfloat16)
    return onp.dtype(dtype)


def default_rtol(dtype=onp.float32):
    dtype = _as_dtype(dtype)
    if dtype.name == "bfloat16":
        return 2e-2
    return {onp.dtype(onp.float16): 1e-2, onp.dtype(onp.float32): 1e-4,
            onp.dtype(onp.float64): 1e-6}.get(dtype, 1e-4)


def default_atol(dtype=onp.float32):
    dtype = _as_dtype(dtype)
    if dtype.name == "bfloat16":
        return 2e-2
    return {onp.dtype(onp.float16): 1e-2, onp.dtype(onp.float32): 1e-5,
            onp.dtype(onp.float64): 1e-7}.get(dtype, 1e-5)


def _np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return onp.asarray(x)


def same(a, b):
    return onp.array_equal(_np(a), _np(b))


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b")):
    a, b = _np(a), _np(b)
    rtol = rtol if rtol is not None else default_rtol(a.dtype)
    atol = atol if atol is not None else default_atol(a.dtype)
    onp.testing.assert_allclose(a, b, rtol=rtol, atol=atol,
                                err_msg=f"{names[0]} vs {names[1]}")


def rand_shape_nd(ndim, dim=10):
    return tuple(onp.random.randint(1, dim + 1, size=ndim))


def rand_ndarray(shape, dtype="float32", ctx=None, scale=1.0):
    return array(onp.random.uniform(-scale, scale, size=shape)
                 .astype(dtype), ctx=ctx)


def check_numeric_gradient(fn, inputs, eps=1e-3, rtol=1e-2, atol=1e-3):
    """Finite-difference gradient check of `fn` (NDArray-in, scalar
    NDArray-out) against the autograd tape."""
    from . import autograd
    inputs = [x if isinstance(x, NDArray) else array(x) for x in inputs]
    for x in inputs:
        x.attach_grad()
    with autograd.record():
        out = fn(*inputs)
    out.backward()
    analytic = [x.grad.asnumpy().copy() for x in inputs]

    for k, x in enumerate(inputs):
        base = x.asnumpy().astype(onp.float64)
        num_grad = onp.zeros_like(base)
        flat = base.reshape(-1)
        ng = num_grad.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + eps
            with autograd.pause():
                fp = float(fn(*[array(base.astype(onp.float32))
                                if j == k else inputs[j]
                                for j in range(len(inputs))]).asscalar())
            flat[i] = orig - eps
            with autograd.pause():
                fm = float(fn(*[array(base.astype(onp.float32))
                                if j == k else inputs[j]
                                for j in range(len(inputs))]).asscalar())
            flat[i] = orig
            ng[i] = (fp - fm) / (2 * eps)
        onp.testing.assert_allclose(analytic[k], num_grad, rtol=rtol,
                                    atol=atol,
                                    err_msg=f"gradient of input {k}")


def check_consistency(fn, inputs, ctx_list=None, dtypes=None, rtol=None,
                      atol=None, grad=True):
    """Cross-backend/dtype consistency check (parity:
    python/mxnet/test_utils.py check_consistency + the GPU-suite pattern of
    tests/python/gpu/test_operator_gpu.py, SURVEY.md §4).

    Runs ``fn`` (NDArrays in → NDArray out) under every (context, dtype)
    configuration and cross-compares outputs — and, when ``grad``, input
    gradients — against the first configuration.  On a TPU host the default
    ctx_list is [cpu, tpu(0)], i.e. the same op executes on both XLA
    backends in ONE process (JAX keeps both live — no suite re-import
    needed, unlike the reference's re-run-under-GPU-scope trick).  On a
    CPU-only host it degrades to a dtype-consistency check.

    Returns the list of (ctx, dtype, outputs, grads) tuples for callers
    that want to inspect further.
    """
    from . import autograd, context as ctx_mod

    if ctx_list is None:
        ctx_list = [ctx_mod.cpu()]
        if ctx_mod.num_tpus():
            ctx_list.append(ctx_mod.tpu(0))
    dtypes = list(dtypes or ["float32"])
    inputs = [_np(x) for x in inputs]

    results = []
    for ctx in ctx_list:
        for dt in dtypes:
            xs = []
            for a in inputs:
                cast = a.astype(_as_dtype(dt)) if onp.issubdtype(
                    a.dtype, onp.floating) else a
                xs.append(array(cast, ctx=ctx))
            if grad:
                for x in xs:
                    x.attach_grad()
                with autograd.record():
                    out = fn(*xs)
                    outs = list(out) if isinstance(out, (tuple, list)) \
                        else [out]
                    head = outs[0].sum() if outs[0].size > 1 else outs[0]
                head.backward()
                grads = [x.grad.asnumpy().astype(onp.float64)
                         if x.grad is not None else None for x in xs]
            else:
                out = fn(*xs)
                outs = list(out) if isinstance(out, (tuple, list)) else [out]
                grads = None
            results.append((ctx, dt,
                            [o.asnumpy().astype(onp.float64) for o in outs],
                            grads))

    ref_ctx, ref_dt, ref_outs, ref_grads = results[0]
    for ctx, dt, outs, grads in results[1:]:
        rt = rtol if rtol is not None else max(default_rtol(dt),
                                               default_rtol(ref_dt))
        at = atol if atol is not None else max(default_atol(dt),
                                               default_atol(ref_dt))
        for i, (a, b) in enumerate(zip(ref_outs, outs)):
            onp.testing.assert_allclose(
                b, a, rtol=rt, atol=at,
                err_msg=f"output {i}: {ctx}/{dt} vs {ref_ctx}/{ref_dt}")
        if grad and ref_grads is not None:
            for i, (a, b) in enumerate(zip(ref_grads, grads)):
                if a is None or b is None:
                    continue
                onp.testing.assert_allclose(
                    b, a, rtol=rt, atol=at,
                    err_msg=f"grad {i}: {ctx}/{dt} vs {ref_ctx}/{ref_dt}")
    return results


def default_context():
    """Current default device scope (parity: mx.test_utils.default_context)."""
    from . import context as ctx_mod
    return ctx_mod.current_context()


def set_default_context(ctx):
    """Pin the process default context (parity: set_default_context —
    how the upstream GPU suite re-ran the CPU tests under another
    device; pairs with MXNET_TPU_TEST_PLATFORM=tpu here)."""
    from . import context as ctx_mod
    stack = getattr(ctx_mod._state, "stack", None)
    if stack:
        stack[-1] = ctx
    else:
        ctx_mod._push_context(ctx)


def list_gpus():
    """Indices of visible accelerators (parity: mx.test_utils.list_gpus —
    'gpu' aliases the TPU here, SURVEY §7.1 device mapping)."""
    from . import context as ctx_mod
    return list(range(ctx_mod.num_gpus()))


def download(url, fname=None, dirname=None, overwrite=False):
    """Parity: mx.test_utils.download.  This image has no network egress,
    so only already-present files resolve; otherwise a clear error."""
    import os
    fname = fname or url.split("/")[-1]
    if dirname:
        fname = os.path.join(dirname, fname)
    if os.path.exists(fname):
        if overwrite:
            import warnings
            warnings.warn(
                f"download({url!r}, overwrite=True): no network egress in "
                f"this environment — using the existing {fname!r} "
                "unrefreshed")
        return fname
    raise _base_error(
        f"download({url!r}): no network egress in this environment and "
        f"{fname!r} does not exist locally")


def _base_error(msg):
    from . import base
    return base.MXNetError(msg)


def get_mnist():
    """MNIST as numpy dict (parity: mx.test_utils.get_mnist).  Falls back
    to the deterministic synthetic surrogate when raw files are absent
    (same data the gluon MNIST dataset serves — hermetic, no egress)."""
    from .gluon.data.vision import MNIST
    tr, te = MNIST(train=True), MNIST(train=False)

    def arr(x):
        return x.asnumpy() if isinstance(x, NDArray) else onp.asarray(x)

    return {
        "train_data": arr(tr._data).reshape(-1, 1, 28, 28)
        .astype(onp.float32) / 255.0,
        "train_label": arr(tr._label).ravel(),
        "test_data": arr(te._data).reshape(-1, 1, 28, 28)
        .astype(onp.float32) / 255.0,
        "test_label": arr(te._label).ravel(),
    }


def get_mnist_iterator(batch_size, input_shape, num_parts=1, part_index=0):
    """(train_iter, val_iter) NDArrayIters over MNIST (parity:
    mx.test_utils.get_mnist_iterator)."""
    from .io import NDArrayIter
    mnist = get_mnist()
    shape = (-1,) + tuple(input_shape)
    tr_data = mnist["train_data"].reshape(shape)
    te_data = mnist["test_data"].reshape(shape)
    if num_parts > 1:
        n = tr_data.shape[0] // num_parts
        sl = slice(part_index * n, (part_index + 1) * n)
        tr_data, tr_label = tr_data[sl], mnist["train_label"][sl]
    else:
        tr_label = mnist["train_label"]
    train = NDArrayIter(tr_data, tr_label, batch_size, shuffle=True)
    val = NDArrayIter(te_data, mnist["test_label"], batch_size)
    return train, val


def with_seed(seed=None):
    """Decorator seeding python/numpy/framework RNGs per test (parity:
    tests/python/unittest/common.py)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            s = seed if seed is not None else onp.random.randint(0, 2**31)
            pyrandom.seed(s)
            onp.random.seed(s)
            _random.seed(s)
            try:
                return fn(*args, **kwargs)
            except Exception:
                print(f"Test failed with seed {s}")
                raise
        return wrapper

    return deco
