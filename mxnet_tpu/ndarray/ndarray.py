"""NDArray: imperative, mutable-looking tensor facade over ``jax.Array``.

Parity target: ``include/mxnet/ndarray.h`` + ``python/mxnet/ndarray/ndarray.py``
(see SURVEY.md §2.1, §7.1).  TPU-first design decisions:

- The payload is an **immutable** ``jax.Array`` (or a JAX tracer while inside
  a hybridized/jitted trace).  "In-place" mutation rebinds the payload
  (functional SSA under the hood) — this is what makes the same op code work
  both eagerly and under ``jax.jit`` tracing, replacing MXNet's
  engine-var/version machinery wholesale: XLA async dispatch already gives the
  compute/copy overlap the threaded engine existed for.
- **Views** (basic slicing) carry a reference to their base plus the index;
  reads re-slice the base lazily, writes scatter into the base and rebind it.
  This reproduces MXNet's aliasing semantics (``y = x[1:3]; y += 1`` mutates
  ``x``) without shared mutable memory.
- **Async semantics**: JAX dispatch is already asynchronous;
  ``wait_to_read()`` maps to ``jax.block_until_ready`` — same contract as the
  dependency engine's ``WaitForVar``.
- **Autograd**: when recording, every dispatched op creates a tape node (see
  ``mxnet_tpu.autograd.tape``); input values are captured as immutable jax
  arrays, so later in-place rebinds can never corrupt the backward pass (a
  class of bug MXNet guards against with version counters).
"""
from __future__ import annotations

import numbers
from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as onp

from .. import base as _base
from ..context import Context, current_context

__all__ = ["NDArray", "array", "from_jax", "zeros", "ones", "full", "empty",
           "arange", "eye", "linspace", "concatenate"]


def _is_jax_value(x) -> bool:
    return isinstance(x, (jax.Array, jax.core.Tracer))


class NDArray:
    __slots__ = ("_data", "_ctx", "_base", "_key", "_node", "_grad",
                 "_mask", "__weakref__")

    def __init__(self, data, ctx: Optional[Context] = None,
                 _base_arr: "Optional[NDArray]" = None, _key=None):
        self._base = _base_arr
        self._key = _key
        self._node = None      # autograd tape node (or None)
        self._grad = None      # NDArray gradient buffer once attach_grad'd
        self._ctx = ctx or current_context()
        if _base_arr is not None:
            self._data = None  # view: value derived from base lazily
        else:
            self._data = data

    # ------------------------------------------------------------------ value
    @property
    def jax(self):
        """The current jax.Array value (resolving views)."""
        if self._base is not None:
            return self._base.jax[self._key]
        return self._data

    def _rebind(self, new_value, node=None):
        """In-place mutation: rebind payload (or scatter into view base)."""
        if self._base is not None:
            base_new = self._base.jax.at[self._key].set(
                jnp.asarray(new_value, dtype=self._base.dtype))
            self._base._rebind(base_new, node=None)
            return
        self._data = new_value
        self._node = node

    # ---------------------------------------------------------------- basics
    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self.jax.shape)

    @property
    def dtype(self):
        return onp.dtype(self.jax.dtype)

    @property
    def size(self) -> int:
        return int(onp.prod(self.shape)) if self.shape else 1

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def context(self) -> Context:
        return self._ctx

    ctx = context
    device = context

    @property
    def stype(self) -> str:
        return "default"  # sparse storage types are handled by sparse module

    @property
    def T(self) -> "NDArray":
        from . import ops
        return ops.transpose(self)

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __repr__(self):
        try:
            body = str(self.asnumpy())
        except Exception:  # tracer
            body = f"<traced {self.shape} {self.dtype}>"
        return f"\n{body}\n<NDArray {'x'.join(map(str, self.shape))} @{self._ctx}>"

    # ------------------------------------------------------------- transfers
    def asnumpy(self) -> onp.ndarray:
        """Synchronizing device→host copy (MXNet's WaitToRead + copy).

        Always returns an OWNED, writable array.  On the CPU backend
        ``np.asarray(jax_array)`` is a zero-copy read-only view of the
        device buffer — and XLA donation (``ShardedTrainer(donate=True)``,
        the serving cache) reuses that memory without regard for live
        numpy views, so a supposedly-snapshotted value would silently
        change under the caller.  The MXNet contract is a copy; pay the
        memcpy (TPU's device→host transfer already owns its buffer, so
        nothing is copied twice)."""
        v = self.jax
        if isinstance(v, jax.core.Tracer):
            raise _base.MXNetError(
                "asnumpy() called inside a hybridized/jitted trace; this "
                "graph-breaks. Use .item()/asnumpy() outside hybridize.")
        a = onp.asarray(v)
        if a.base is not None or not a.flags.writeable:
            a = onp.array(a)
        return a

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def tolist(self):
        return self.asnumpy().tolist()

    def wait_to_read(self):
        v = self.jax
        if not isinstance(v, jax.core.Tracer):
            jax.block_until_ready(v)

    wait_to_write = wait_to_read

    def copy(self) -> "NDArray":
        return NDArray(self.jax, ctx=self._ctx)

    def copyto(self, other) -> "NDArray":
        if isinstance(other, Context):
            return self.as_in_context(other)
        other._rebind(jnp.asarray(self.jax, dtype=other.dtype))
        return other

    def as_in_context(self, ctx: Context) -> "NDArray":
        v = self.jax
        if not isinstance(v, jax.core.Tracer):
            v = jax.device_put(v, ctx.jax_device)
        return NDArray(v, ctx=ctx)

    as_in_ctx = as_in_context
    to_device = as_in_context

    def astype(self, dtype, copy=True) -> "NDArray":
        dt = _base.canonical_dtype(dtype)
        if not copy and dt == self.dtype:
            return self
        from . import ops
        return ops.cast(self, dtype=dt)

    # ------------------------------------------------------------- autograd
    def attach_grad(self, grad_req: str = "write", stype=None):
        from ..autograd import tape
        if stype == "row_sparse":
            # compact zero: no (rows, dim) dense buffer is ever allocated
            from .sparse import RowSparseNDArray
            self._grad = RowSparseNDArray.from_components(
                jnp.zeros((0,) + self.shape[1:], self.jax.dtype),
                jnp.zeros((0,), jnp.int32), self.shape, ctx=self._ctx)
        else:
            self._grad = NDArray(jnp.zeros_like(self.jax), ctx=self._ctx)
        self._node = tape.LeafNode(self, grad_req)

    @property
    def grad(self) -> "Optional[NDArray]":
        return self._grad

    def detach(self) -> "NDArray":
        out = NDArray(self.jax, ctx=self._ctx)
        return out

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        from .. import autograd
        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    # ------------------------------------------------------------- indexing
    def _canonical_key(self, key):
        if isinstance(key, tuple):
            return self, tuple(self._index_key(k) for k in key)
        return self, self._index_key(key)

    def _index_key(self, k):
        if isinstance(k, NDArray):
            kj = k.jax
            if kj.dtype == jnp.bool_:
                return kj
            if getattr(k, "_mask", False):
                # result of a comparison op: boolean-mask semantics
                return kj.astype(bool)
            if not jnp.issubdtype(kj.dtype, jnp.integer):
                # MXNet comparisons yield float 0/1 arrays: a same-shaped
                # float key is the x[x > 5] mask idiom, else a fancy index
                if kj.ndim > 0 and tuple(kj.shape) == self.shape:
                    return kj.astype(bool)
                return kj.astype(jnp.int32)
            return kj
        return k

    @staticmethod
    def _is_basic_index(key) -> bool:
        """Basic (view-forming) index: ints/slices/ellipsis/None tuples."""
        items = key if isinstance(key, tuple) else (key,)
        return all(isinstance(k, (int, onp.integer, slice, type(Ellipsis),
                                  type(None))) for k in items)

    def __getitem__(self, key):
        _, key = self._canonical_key(key)
        from . import ops
        if self._is_basic_index(key) and not _base.is_recording():
            # aliasing view: writes through this object hit the base
            return NDArray(None, ctx=self._ctx, _base_arr=self._root_base(),
                           _key=self._compose_key(key))
        return ops._getitem(self, key)

    def _root_base(self):
        return self if self._base is None else self._base

    def _compose_key(self, key):
        if self._base is None:
            return key
        # view-of-view: compose by materializing through jnp indexing chain
        # (correctness first; deep view chains are rare in real scripts)
        return _ComposedKey(self._key, key)

    def __setitem__(self, key, value):
        _, key = self._canonical_key(key)
        if isinstance(value, NDArray):
            vj = value.jax
        elif isinstance(value, (numbers.Number, bool)):
            vj = value
        else:
            vj = jnp.asarray(value)
        if key is Ellipsis or key == slice(None):
            tgt = self.jax
            new = jnp.broadcast_to(jnp.asarray(vj, dtype=self.dtype),
                                   tgt.shape)
            if _base.is_recording() and isinstance(vj, (jax.Array, jax.core.Tracer)):
                from . import ops
                ops._setitem_full(self, value if isinstance(value, NDArray) else NDArray(new))
            else:
                self._rebind(new)
            return
        if _base.is_recording():
            from . import ops
            ops._setitem(self, key, value if isinstance(value, NDArray)
                         else NDArray(jnp.asarray(vj)))
        else:
            self._rebind(self.jax.at[key].set(
                jnp.asarray(vj, dtype=self.dtype)))

    # ---------------------------------------------------------- arithmetic
    def _binop(self, name, other, reflected=False):
        from . import ops
        fn = getattr(ops, name)
        if reflected:
            return fn(other, self)
        return fn(self, other)

    def __add__(self, o): return self._binop("add", o)
    def __radd__(self, o): return self._binop("add", o, True)
    def __sub__(self, o): return self._binop("subtract", o)
    def __rsub__(self, o): return self._binop("subtract", o, True)
    def __mul__(self, o): return self._binop("multiply", o)
    def __rmul__(self, o): return self._binop("multiply", o, True)
    def __truediv__(self, o): return self._binop("divide", o)
    def __rtruediv__(self, o): return self._binop("divide", o, True)
    def __floordiv__(self, o): return self._binop("floor_divide", o)
    def __rfloordiv__(self, o): return self._binop("floor_divide", o, True)
    def __mod__(self, o): return self._binop("mod", o)
    def __rmod__(self, o): return self._binop("mod", o, True)
    def __pow__(self, o): return self._binop("power", o)
    def __rpow__(self, o): return self._binop("power", o, True)
    def __matmul__(self, o): return self._binop("matmul", o)
    def __rmatmul__(self, o): return self._binop("matmul", o, True)
    def __neg__(self):
        from . import ops
        return ops.negative(self)
    def __abs__(self):
        from . import ops
        return ops.abs(self)

    def _inplace(self, name, other):
        res = self._binop(name, other)
        self._rebind(res.jax, node=res._node)
        return self

    def __iadd__(self, o): return self._inplace("add", o)
    def __isub__(self, o): return self._inplace("subtract", o)
    def __imul__(self, o): return self._inplace("multiply", o)
    def __itruediv__(self, o): return self._inplace("divide", o)
    def __imod__(self, o): return self._inplace("mod", o)
    def __ipow__(self, o): return self._inplace("power", o)

    def __eq__(self, o): return self._binop("equal", o)
    def __ne__(self, o): return self._binop("not_equal", o)
    def __lt__(self, o): return self._binop("lesser", o)
    def __le__(self, o): return self._binop("lesser_equal", o)
    def __gt__(self, o): return self._binop("greater", o)
    def __ge__(self, o): return self._binop("greater_equal", o)

    def __hash__(self):
        return id(self)

    def __bool__(self):
        if self.size == 1:
            return bool(self.asscalar())
        raise ValueError("The truth value of an NDArray with multiple "
                         "elements is ambiguous.")

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __index__(self):
        return int(self.asscalar())

    # --------------------------------------------------- method-style ops
    def _unary(self, name, **kw):
        from . import ops
        return getattr(ops, name)(self, **kw)

    def reshape(self, *shape, **kwargs):
        from . import ops
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        if "shape" in kwargs:
            shape = kwargs["shape"]
        return ops.reshape(self, shape=shape)

    def reshape_like(self, other):
        return self.reshape(other.shape)

    def transpose(self, *axes):
        from . import ops
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return ops.transpose(self, axes=axes if axes else None)

    def swapaxes(self, a1, a2): return self._unary("swapaxes", dim1=a1, dim2=a2)
    def flatten(self): return self._unary("flatten")
    def expand_dims(self, axis): return self._unary("expand_dims", axis=axis)
    def squeeze(self, axis=None): return self._unary("squeeze", axis=axis)
    def broadcast_to(self, shape): return self._unary("broadcast_to", shape=shape)
    def broadcast_like(self, other): return self.broadcast_to(other.shape)
    def sum(self, axis=None, keepdims=False):
        return self._unary("sum", axis=axis, keepdims=keepdims)
    def mean(self, axis=None, keepdims=False):
        return self._unary("mean", axis=axis, keepdims=keepdims)
    def max(self, axis=None, keepdims=False):
        return self._unary("max", axis=axis, keepdims=keepdims)
    def min(self, axis=None, keepdims=False):
        return self._unary("min", axis=axis, keepdims=keepdims)
    def prod(self, axis=None, keepdims=False):
        return self._unary("prod", axis=axis, keepdims=keepdims)
    def argmax(self, axis=None): return self._unary("argmax", axis=axis)
    def argmin(self, axis=None): return self._unary("argmin", axis=axis)

    def _np_method(self, name, *args, **kwargs):
        """Delegate to the mx.np function of the same name (numpy-parity
        methods whose op lives only in the np namespace)."""
        from .. import numpy as _np
        return getattr(_np, name)(self, *args, **kwargs)

    def std(self, axis=None, keepdims=False):
        return self._np_method("std", axis=axis, keepdims=keepdims)
    def var(self, axis=None, keepdims=False):
        return self._np_method("var", axis=axis, keepdims=keepdims)
    def cumsum(self, axis=None):
        return self._np_method("cumsum", axis=axis)
    # sort/argsort follow NUMPY semantics here (differentiable sort,
    # integer indices); the legacy float32-index mx.nd.argsort op keeps
    # its 1.x behavior as a free function
    def sort(self, axis=-1):
        return self._np_method("sort", axis=axis)
    def argsort(self, axis=-1):
        return self._np_method("argsort", axis=axis)
    def nonzero(self): return self._np_method("nonzero")
    def all(self, axis=None, keepdims=False):
        return self._np_method("all", axis=axis, keepdims=keepdims)
    def any(self, axis=None, keepdims=False):
        return self._np_method("any", axis=axis, keepdims=keepdims)
    def ravel(self): return self._np_method("ravel")

    @property
    def itemsize(self):
        import numpy as _onp
        return _onp.dtype(self.dtype).itemsize

    @property
    def flat(self):
        # read-only: a writable .flat would mutate only a host copy —
        # raising beats silently discarding writes
        a = self.asnumpy()
        a.flags.writeable = False
        return a.flat
    def norm(self, ord=2, axis=None, keepdims=False):
        return self._unary("norm", ord=ord, axis=axis, keepdims=keepdims)
    def clip(self, a_min=None, a_max=None):
        return self._unary("clip", a_min=a_min, a_max=a_max)
    def abs(self): return self._unary("abs")
    def exp(self): return self._unary("exp")
    def log(self): return self._unary("log")
    def sqrt(self): return self._unary("sqrt")
    def square(self): return self._unary("square")
    def sign(self): return self._unary("sign")
    def round(self): return self._unary("round")
    def floor(self): return self._unary("floor")
    def ceil(self): return self._unary("ceil")
    def sigmoid(self): return self._unary("sigmoid")
    def tanh(self): return self._unary("tanh")
    def relu(self): return self._unary("relu")
    def softmax(self, axis=-1): return self._unary("softmax", axis=axis)
    def log_softmax(self, axis=-1): return self._unary("log_softmax", axis=axis)
    def one_hot(self, depth, **kw): return self._unary("one_hot", depth=depth, **kw)
    def take(self, indices, axis=0):
        from . import ops
        return ops.take(self, indices, axis=axis)
    def dot(self, other):
        from . import ops
        return ops.dot(self, other)
    def slice_axis(self, axis, begin, end):
        from . import ops
        return ops.slice_axis(self, axis=axis, begin=begin, end=end)
    def split(self, num_outputs, axis=1, squeeze_axis=False):
        from . import ops
        return ops.split(self, num_outputs=num_outputs, axis=axis,
                         squeeze_axis=squeeze_axis)
    def tile(self, reps): return self._unary("tile", reps=reps)
    def repeat(self, repeats, axis=None):
        return self._unary("repeat", repeats=repeats, axis=axis)
    def flip(self, axis): return self._unary("flip", axis=axis)
    def pad(self, *a, **kw): return self._unary("pad", *a, **kw)
    def zeros_like(self): return self._unary("zeros_like")
    def ones_like(self): return self._unary("ones_like")
    def tostype(self, stype):
        if stype == "default":
            return self
        from .sparse import cast_storage
        return cast_storage(self, stype)

    # numpy-protocol interop
    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    def __dlpack__(self, **kw):
        return self.jax.__dlpack__(**kw)


class _ComposedKey:
    """Index composition for view-of-view (read path materializes)."""

    def __init__(self, outer, inner):
        self.outer = outer
        self.inner = inner


# patched __getitem__ on jax values for composed keys
_orig_jax_getitem = None


def _resolve_key(value, key):
    if isinstance(key, _ComposedKey):
        return _resolve_key(_resolve_key(value, key.outer), key.inner)
    return value[key]


# Make NDArray.jax handle composed keys.
def _jax_prop(self):
    if self._base is not None:
        return _resolve_key(self._base.jax, self._key)
    return self._data


NDArray.jax = property(_jax_prop)


def _rebind_view(self, new_value, node=None):
    if self._base is not None:
        key = self._key
        if isinstance(key, _ComposedKey):
            outer_val = _resolve_key(self._base.jax, key.outer)
            updated = outer_val.at[key.inner].set(
                jnp.asarray(new_value, dtype=outer_val.dtype))
            base_new = self._base.jax.at[key.outer].set(updated)
        else:
            base_new = self._base.jax.at[key].set(
                jnp.asarray(new_value, dtype=self._base.dtype))
        self._base._rebind(base_new, node=None)
        return
    self._data = new_value
    if node is None:
        # attach_grad leaf-ness survives non-recorded mutation (optimizer
        # updates, set_data); only a recorded op result replaces the node
        from ..autograd.tape import LeafNode
        if isinstance(self._node, LeafNode):
            return
    self._node = node


NDArray._rebind = _rebind_view


import contextlib


@contextlib.contextmanager
def swap_values(nds, values):
    """Temporarily rebind each NDArray's payload to a traced value.

    The functionalization primitive shared by CachedOp, ShardedTrainer and
    the driver entry: inside the scope each NDArray in `nds` holds the
    corresponding (usually tracer) value with no autograd node; on exit the
    original payload/node are restored.  Mutations made inside the scope are
    visible via each NDArray's current payload before exit (callers read them
    to functionalize aux-state updates such as BatchNorm running stats).
    """
    saved = [(d, d._data, d._node) for d in nds]
    for d, v in zip(nds, values):
        d._data, d._node = v, None
    try:
        yield saved
    finally:
        for d, old, node in saved:
            d._data, d._node = old, node


# ----------------------------------------------------------------- creation

def _put(value, ctx: Optional[Context]) -> jax.Array:
    ctx = ctx or current_context()
    if isinstance(value, jax.core.Tracer):
        return value
    return jax.device_put(value, ctx.jax_device)


def array(source, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    if isinstance(source, NDArray):
        source = source.jax
    dt = _base.canonical_dtype(dtype) if dtype is not None else None
    if not _is_jax_value(source):
        keep_dtype = isinstance(source, onp.ndarray)
        source = onp.asarray(source, dtype=dt)
        if dt is None:
            if source.dtype == onp.float64:
                source = source.astype(onp.float32)
            elif not keep_dtype:
                # MXNet: python lists default to float32; numpy arrays keep
                # their dtype (python/mxnet/ndarray/ndarray.py array())
                source = source.astype(onp.float32)
    elif dt is not None:
        source = jnp.asarray(source, dtype=dt)
    ctx = ctx or current_context()
    return NDArray(_put(source, ctx), ctx=ctx)


def from_jax(value, ctx: Optional[Context] = None) -> NDArray:
    return NDArray(value, ctx=ctx or current_context())


def zeros(shape, ctx=None, dtype="float32") -> NDArray:
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return array(onp.zeros(shape, dtype=_base.canonical_dtype(dtype)), ctx=ctx)


def ones(shape, ctx=None, dtype="float32") -> NDArray:
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return array(onp.ones(shape, dtype=_base.canonical_dtype(dtype)), ctx=ctx)


def full(shape, val, ctx=None, dtype="float32") -> NDArray:
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return array(onp.full(shape, val, dtype=_base.canonical_dtype(dtype)),
                 ctx=ctx)


def empty(shape, ctx=None, dtype="float32") -> NDArray:
    return zeros(shape, ctx=ctx, dtype=dtype)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None,
           dtype="float32") -> NDArray:
    arr = onp.arange(start, stop, step, dtype=_base.canonical_dtype(dtype))
    if repeat > 1:
        arr = onp.repeat(arr, repeat)
    return array(arr, ctx=ctx)


def eye(N, M=None, k=0, ctx=None, dtype="float32") -> NDArray:
    return array(onp.eye(N, M, k, dtype=_base.canonical_dtype(dtype)), ctx=ctx)


def linspace(start, stop, num, endpoint=True, ctx=None,
             dtype="float32") -> NDArray:
    return array(onp.linspace(start, stop, num, endpoint=endpoint,
                              dtype=_base.canonical_dtype(dtype)), ctx=ctx)


def concatenate(arrays, axis=0) -> NDArray:
    from . import ops
    return ops.concat(*arrays, dim=axis)
