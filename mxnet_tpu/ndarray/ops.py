"""The ``nd`` operator namespace.

Parity target: the generated ``mx.nd.*`` wrappers over ``src/operator/**``
(SURVEY.md §2.3, §2.6).  TPU-first: each op is a pure JAX function dispatched
through :func:`invoke`, which (a) unwraps NDArray→jax.Array, (b) captures a
``jax.vjp`` pullback when autograd is recording, (c) wraps outputs.  Under
hybridize the same code path runs on tracers, so the whole op surface lowers
into a single XLA computation — the CachedOp role with zero extra machinery.

XLA fuses elementwise chains into matmul/conv epilogues on its own; only ops
XLA cannot express well (flash attention) get Pallas kernels (mxnet_tpu.ops).
"""
from __future__ import annotations

import builtins
import functools
import math
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as onp
from jax import lax

from .. import base as _base
from .. import random as _random
from ..autograd.tape import OpNode, OutRef, node_of
from ..context import current_context
from .ndarray import NDArray, array, from_jax

__all__: list = []  # populated by _export


def _export(fn):
    __all__.append(fn.__name__)
    return fn


# ---------------------------------------------------------------- dispatcher

_amp_mod = None


def _amp_policy():
    global _amp_mod
    if _amp_mod is None:
        from .. import amp as _a
        _amp_mod = _a
    return _amp_mod.current_policy()


# optional op-observation hooks (mx.monitor.Monitor installs here); each
# is called hook(op_name, output_NDArrays) after a successful dispatch
_invoke_hooks = []


def invoke(name, pure_fn, nd_inputs, nout=1, ctx=None, differentiable=True):
    """Dispatch a pure jax function over NDArray inputs with autograd."""
    arrs = tuple(x.jax for x in nd_inputs)
    pol = _amp_policy()
    if pol is not None:
        arrs = pol.cast_args(name, arrs)
    recording = _base.is_recording() and differentiable
    in_nodes = [node_of(x) for x in nd_inputs] if recording else None
    needs_grad = recording and any(n is not None for n in in_nodes)
    ctx = ctx or (nd_inputs[0].context if nd_inputs else current_context())
    try:
        platform = ctx.jax_device.platform
    except Exception:   # backend not up yet / device resolution failed
        platform = None
    with _base.executing_on(platform):
        if needs_grad:
            outs, vjp_fn = jax.vjp(pure_fn, *arrs)
        else:
            outs = pure_fn(*arrs)
    multi = isinstance(outs, (tuple, list))
    outs_list = list(outs) if multi else [outs]
    res = [NDArray(o, ctx=ctx) for o in outs_list]
    if needs_grad:
        node = OpNode(
            vjp_fn, in_nodes, len(res), name=name,
            out_avals=[jax.ShapeDtypeStruct(o.shape, o.dtype)
                       for o in outs_list])
        for i, r in enumerate(res):
            r._node = OutRef(node, i)
    if _invoke_hooks:
        for h in tuple(_invoke_hooks):
            h(name, res)
    return res if multi else res[0]


def _as_nd(x):
    if isinstance(x, NDArray):
        return x
    return array(x)


def _unary_op(name, jfn, differentiable=True):
    def op(data, out=None, **ignored):
        data = _as_nd(data)
        r = invoke(name, jfn, [data], differentiable=differentiable)
        if out is not None:
            out._rebind(r.jax, node=r._node)
            return out
        return r
    op.__name__ = name
    return _export(op)


def _binary_op(name, jfn, differentiable=True, is_mask=False):
    def op(lhs, rhs, out=None, **ignored):
        if isinstance(lhs, NDArray) and isinstance(rhs, NDArray):
            r = invoke(name, jfn, [lhs, rhs], differentiable=differentiable)
        elif isinstance(lhs, NDArray):
            r = invoke(name, lambda a: jfn(a, rhs), [lhs],
                       differentiable=differentiable)
        elif isinstance(rhs, NDArray):
            r = invoke(name, lambda b: jfn(lhs, b), [rhs],
                       differentiable=differentiable)
        else:
            return jfn(lhs, rhs)
        if is_mask:
            r._mask = True
        if out is not None:
            out._rebind(r.jax, node=r._node)
            return out
        return r
    op.__name__ = name
    return _export(op)


def _kw_op(name, make_fn, differentiable=True, n_in=1):
    """Op whose pure fn depends on kwargs: make_fn(**kw) -> jax fn."""
    def op(*inputs, **kw):
        nds = [_as_nd(x) for x in inputs[:n_in]]
        return invoke(name, make_fn(**kw), nds,
                      differentiable=differentiable)
    op.__name__ = name
    return _export(op)


# ------------------------------------------------------------- element-wise

add = _binary_op("add", jnp.add)
subtract = _binary_op("subtract", jnp.subtract)
multiply = _binary_op("multiply", jnp.multiply)
divide = _binary_op("divide", jnp.divide)
floor_divide = _binary_op("floor_divide", jnp.floor_divide,
                          differentiable=False)
mod = _binary_op("mod", jnp.mod)
power = _binary_op("power", jnp.power)
maximum = _binary_op("maximum", jnp.maximum)
minimum = _binary_op("minimum", jnp.minimum)
hypot = _binary_op("hypot", jnp.hypot)
arctan2 = _binary_op("arctan2", jnp.arctan2)
equal = _binary_op("equal", lambda a, b: jnp.equal(a, b).astype(jnp.result_type(a)), differentiable=False, is_mask=True)
not_equal = _binary_op("not_equal", lambda a, b: jnp.not_equal(a, b).astype(jnp.result_type(a)), differentiable=False, is_mask=True)
greater = _binary_op("greater", lambda a, b: jnp.greater(a, b).astype(jnp.result_type(a)), differentiable=False, is_mask=True)
greater_equal = _binary_op("greater_equal", lambda a, b: jnp.greater_equal(a, b).astype(jnp.result_type(a)), differentiable=False, is_mask=True)
lesser = _binary_op("lesser", lambda a, b: jnp.less(a, b).astype(jnp.result_type(a)), differentiable=False, is_mask=True)
lesser_equal = _binary_op("lesser_equal", lambda a, b: jnp.less_equal(a, b).astype(jnp.result_type(a)), differentiable=False, is_mask=True)
logical_and = _binary_op("logical_and", lambda a, b: jnp.logical_and(a, b).astype(jnp.float32), differentiable=False)
logical_or = _binary_op("logical_or", lambda a, b: jnp.logical_or(a, b).astype(jnp.float32), differentiable=False)
logical_xor = _binary_op("logical_xor", lambda a, b: jnp.logical_xor(a, b).astype(jnp.float32), differentiable=False)

# broadcast_* aliases (MXNet names)
for _nm, _f in [("broadcast_add", "add"), ("broadcast_sub", "subtract"),
                ("broadcast_mul", "multiply"), ("broadcast_div", "divide"),
                ("broadcast_power", "power"), ("broadcast_maximum", "maximum"),
                ("broadcast_minimum", "minimum"), ("broadcast_mod", "mod"),
                ("broadcast_equal", "equal"),
                ("broadcast_not_equal", "not_equal"),
                ("broadcast_greater", "greater"),
                ("broadcast_greater_equal", "greater_equal"),
                ("broadcast_lesser", "lesser"),
                ("broadcast_lesser_equal", "lesser_equal"),
                ("broadcast_logical_and", "logical_and"),
                ("broadcast_logical_or", "logical_or"),
                ("broadcast_logical_xor", "logical_xor"),
                ("elemwise_add", "add"), ("elemwise_sub", "subtract"),
                ("elemwise_mul", "multiply"), ("elemwise_div", "divide")]:
    globals()[_nm] = globals()[_f]
    __all__.append(_nm)

negative = _unary_op("negative", jnp.negative)
abs = _unary_op("abs", jnp.abs)
sign = _unary_op("sign", jnp.sign, differentiable=False)
round = _unary_op("round", jnp.round, differentiable=False)
rint = _unary_op("rint", jnp.rint, differentiable=False)
floor = _unary_op("floor", jnp.floor, differentiable=False)
ceil = _unary_op("ceil", jnp.ceil, differentiable=False)
trunc = _unary_op("trunc", jnp.trunc, differentiable=False)
fix = _unary_op("fix", jnp.trunc, differentiable=False)
exp = _unary_op("exp", jnp.exp)
expm1 = _unary_op("expm1", jnp.expm1)
log = _unary_op("log", jnp.log)
log10 = _unary_op("log10", jnp.log10)
log2 = _unary_op("log2", jnp.log2)
log1p = _unary_op("log1p", jnp.log1p)
sqrt = _unary_op("sqrt", jnp.sqrt)
rsqrt = _unary_op("rsqrt", lax.rsqrt)
cbrt = _unary_op("cbrt", jnp.cbrt)
rcbrt = _unary_op("rcbrt", lambda x: 1.0 / jnp.cbrt(x))
square = _unary_op("square", jnp.square)
reciprocal = _unary_op("reciprocal", jnp.reciprocal)
sin = _unary_op("sin", jnp.sin)
cos = _unary_op("cos", jnp.cos)
tan = _unary_op("tan", jnp.tan)
arcsin = _unary_op("arcsin", jnp.arcsin)
arccos = _unary_op("arccos", jnp.arccos)
arctan = _unary_op("arctan", jnp.arctan)
sinh = _unary_op("sinh", jnp.sinh)
cosh = _unary_op("cosh", jnp.cosh)
tanh = _unary_op("tanh", jnp.tanh)
arcsinh = _unary_op("arcsinh", jnp.arcsinh)
arccosh = _unary_op("arccosh", jnp.arccosh)
arctanh = _unary_op("arctanh", jnp.arctanh)
degrees = _unary_op("degrees", jnp.degrees)
radians = _unary_op("radians", jnp.radians)
erf = _unary_op("erf", jax.scipy.special.erf)
erfinv = _unary_op("erfinv", jax.scipy.special.erfinv)
gamma = _unary_op("gamma", lambda x: jnp.exp(jax.scipy.special.gammaln(x)))
gammaln = _unary_op("gammaln", jax.scipy.special.gammaln)
sigmoid = _unary_op("sigmoid", jax.nn.sigmoid)
softsign = _unary_op("softsign", jax.nn.soft_sign)
relu = _unary_op("relu", jax.nn.relu)
softplus = _unary_op("softplus", jax.nn.softplus)
logical_not = _unary_op("logical_not", lambda x: jnp.logical_not(x).astype(jnp.float32), differentiable=False)
isnan = _unary_op("isnan", lambda x: jnp.isnan(x).astype(jnp.float32), differentiable=False)
isinf = _unary_op("isinf", lambda x: jnp.isinf(x).astype(jnp.float32), differentiable=False)
isfinite = _unary_op("isfinite", lambda x: jnp.isfinite(x).astype(jnp.float32), differentiable=False)
zeros_like = _unary_op("zeros_like", jnp.zeros_like, differentiable=False)
ones_like = _unary_op("ones_like", jnp.ones_like, differentiable=False)
identity = _unary_op("identity", lambda x: x)


@_export
def clip(data, a_min=None, a_max=None, out=None, **kw):
    data = _as_nd(data)
    r = invoke("clip", lambda x: jnp.clip(x, a_min, a_max), [data])
    if out is not None:
        out._rebind(r.jax, node=r._node)
        return out
    return r


@_export
def cast(data, dtype, out=None):
    dt = jnp.dtype(_base.canonical_dtype(dtype))
    data = _as_nd(data)
    r = invoke("cast", lambda x: x.astype(dt), [data])
    if out is not None:
        out._rebind(r.jax, node=r._node)
        return out
    return r


Cast = cast
__all__.append("Cast")


@_export
def where(condition, x, y):
    condition, x, y = _as_nd(condition), _as_nd(x), _as_nd(y)
    return invoke("where",
                  lambda c, a, b: jnp.where(c.astype(bool), a, b),
                  [condition, x, y])


# ---------------------------------------------------------------- reductions

def _reduce_op(name, jfn, differentiable=True):
    def op(data, axis=None, keepdims=False, exclude=False, out=None, **kw):
        data = _as_nd(data)
        ax = axis
        if isinstance(ax, (list, tuple)) and len(ax) == 0:
            ax = None
        if exclude and ax is not None:
            axes = (ax,) if isinstance(ax, int) else tuple(ax)
            ax = tuple(i for i in range(data.ndim) if i not in
                       tuple(a % data.ndim for a in axes))
        r = invoke(name, lambda x: jfn(x, axis=ax, keepdims=keepdims),
                   [data], differentiable=differentiable)
        if out is not None:
            out._rebind(r.jax, node=r._node)
            return out
        return r
    op.__name__ = name
    return _export(op)


sum = _reduce_op("sum", jnp.sum)
mean = _reduce_op("mean", jnp.mean)
prod = _reduce_op("prod", jnp.prod)
max = _reduce_op("max", jnp.max)
min = _reduce_op("min", jnp.min)
nansum = _reduce_op("nansum", jnp.nansum)
nanprod = _reduce_op("nanprod", jnp.nanprod)

sum_axis = sum
__all__.append("sum_axis")


@_export
def norm(data, ord=2, axis=None, keepdims=False, out=None):
    data = _as_nd(data)
    def f(x):
        if ord == 2:
            return jnp.sqrt(jnp.sum(jnp.square(x), axis=axis,
                                    keepdims=keepdims))
        if ord == 1:
            return jnp.sum(jnp.abs(x), axis=axis, keepdims=keepdims)
        raise ValueError("norm only supports ord=1,2")
    return invoke("norm", f, [data])


@_export
def argmax(data, axis=None, keepdims=False):
    data = _as_nd(data)
    return invoke("argmax",
                  lambda x: jnp.argmax(x, axis=axis, keepdims=keepdims)
                  .astype(jnp.float32),
                  [data], differentiable=False)


@_export
def argmin(data, axis=None, keepdims=False):
    data = _as_nd(data)
    return invoke("argmin",
                  lambda x: jnp.argmin(x, axis=axis, keepdims=keepdims)
                  .astype(jnp.float32),
                  [data], differentiable=False)


@_export
def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    data = _as_nd(data)
    dt = jnp.dtype(_base.canonical_dtype(dtype))

    def f(x):
        xs = jnp.moveaxis(x, axis, -1)
        vals, idx = lax.top_k(-xs if is_ascend else xs, k)
        if is_ascend:
            vals = -vals
        vals = jnp.moveaxis(vals, -1, axis)
        idx = jnp.moveaxis(idx, -1, axis)
        if ret_typ == "indices":
            return idx.astype(dt)
        if ret_typ == "value":
            return vals
        return (vals, idx.astype(dt))

    return invoke("topk", f, [data], differentiable=False)


@_export
def sort(data, axis=-1, is_ascend=True):
    data = _as_nd(data)
    def f(x):
        s = jnp.sort(x, axis=axis)
        return s if is_ascend else jnp.flip(s, axis=axis)
    return invoke("sort", f, [data], differentiable=False)


@_export
def argsort(data, axis=-1, is_ascend=True, dtype="float32"):
    data = _as_nd(data)
    dt = jnp.dtype(_base.canonical_dtype(dtype))
    def f(x):
        s = jnp.argsort(x, axis=axis)
        if not is_ascend:
            s = jnp.flip(s, axis=axis)
        return s.astype(dt)
    return invoke("argsort", f, [data], differentiable=False)


# ------------------------------------------------------------ linear algebra

@_export
def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    lhs, rhs = _as_nd(lhs), _as_nd(rhs)

    def f(a, b):
        if transpose_a:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_b:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        # MXNet dot: contracts last axis of a with first axis of b
        return jnp.tensordot(a, b, axes=([a.ndim - 1], [0]))

    return invoke("dot", f, [lhs, rhs])


@_export
def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    lhs, rhs = _as_nd(lhs), _as_nd(rhs)

    def f(a, b):
        if transpose_a:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_b:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b)

    return invoke("batch_dot", f, [lhs, rhs])


@_export
def matmul(lhs, rhs):
    lhs, rhs = _as_nd(lhs), _as_nd(rhs)
    return invoke("matmul", jnp.matmul, [lhs, rhs])


@_export
def linalg_gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0):
    A, B = _as_nd(A), _as_nd(B)

    def f(a, b):
        if transpose_a:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_b:
            b = jnp.swapaxes(b, -1, -2)
        return alpha * jnp.matmul(a, b)

    return invoke("linalg_gemm2", f, [A, B])


@_export
def linalg_potrf(A):
    return invoke("linalg_potrf", jnp.linalg.cholesky, [_as_nd(A)])


@_export
def linalg_trsm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0):
    A, B = _as_nd(A), _as_nd(B)

    def f(a, b):
        return alpha * jax.lax.linalg.triangular_solve(
            a, b, left_side=not rightside, lower=lower,
            transpose_a=transpose)

    return invoke("linalg_trsm", f, [A, B])


@_export
def linalg_syrk(A, transpose=False, alpha=1.0):
    A = _as_nd(A)

    def f(a):
        at = jnp.swapaxes(a, -1, -2)
        return alpha * (jnp.matmul(at, a) if transpose else jnp.matmul(a, at))

    return invoke("linalg_syrk", f, [A])


# --------------------------------------------------------------- shape ops

@_export
def reshape(data, shape=None, reverse=False, **kw):
    data = _as_nd(data)
    tgt = _mx_reshape_shape(data.shape, tuple(shape), reverse)
    return invoke("reshape", lambda x: jnp.reshape(x, tgt), [data])


def _mx_reshape_shape(src: Tuple[int, ...], spec: Tuple[int, ...],
                      reverse: bool) -> Tuple[int, ...]:
    """Implements MXNet reshape special codes 0, -1, -2, -3, -4."""
    if reverse:
        rev = _mx_reshape_shape(tuple(reversed(src)),
                                tuple(reversed(spec)), False)
        return tuple(reversed(rev))
    out: list = []
    src_i = 0
    i = 0
    spec = tuple(spec)
    while i < len(spec):
        s = spec[i]
        if s == 0:
            out.append(src[src_i]); src_i += 1
        elif s == -1:
            out.append(-1); src_i += 1
        elif s == -2:
            out.extend(src[src_i:]); src_i = len(src)
        elif s == -3:
            out.append(src[src_i] * src[src_i + 1]); src_i += 2
        elif s == -4:
            a, b = spec[i + 1], spec[i + 2]
            dim = src[src_i]
            if a == -1:
                a = dim // b
            if b == -1:
                b = dim // a
            out.extend([a, b]); src_i += 1; i += 2
        else:
            out.append(int(s)); src_i += 1
        i += 1
    if -1 in out:
        known = 1
        for v in out:
            if v != -1:
                known *= v
        total = 1
        for v in src:
            total *= v
        out[out.index(-1)] = total // known if known else 0
    return tuple(out)


@_export
def transpose(data, axes=None):
    data = _as_nd(data)
    ax = tuple(axes) if axes else None
    return invoke("transpose", lambda x: jnp.transpose(x, ax), [data])


@_export
def swapaxes(data, dim1=0, dim2=1):
    data = _as_nd(data)
    return invoke("swapaxes", lambda x: jnp.swapaxes(x, dim1, dim2), [data])


SwapAxis = swapaxes
__all__.append("SwapAxis")


@_export
def flatten(data):
    data = _as_nd(data)
    n = data.shape[0] if data.ndim else 1
    return invoke("flatten", lambda x: jnp.reshape(x, (n, -1)), [data])


Flatten = flatten
__all__.append("Flatten")


@_export
def expand_dims(data, axis):
    data = _as_nd(data)
    return invoke("expand_dims", lambda x: jnp.expand_dims(x, axis), [data])


@_export
def squeeze(data, axis=None):
    data = _as_nd(data)
    return invoke("squeeze", lambda x: jnp.squeeze(x, axis), [data])


@_export
def broadcast_to(data, shape):
    data = _as_nd(data)
    src = data.shape
    tgt = tuple(s if t == 0 else t for s, t in zip(src, tuple(shape)))
    return invoke("broadcast_to", lambda x: jnp.broadcast_to(x, tgt), [data])


@_export
def broadcast_like(lhs, rhs):
    lhs, rhs = _as_nd(lhs), _as_nd(rhs)
    return invoke("broadcast_like",
                  lambda a, b: jnp.broadcast_to(a, b.shape), [lhs, rhs])


@_export
def reshape_like(lhs, rhs, lhs_begin=None, lhs_end=None, rhs_begin=None,
                 rhs_end=None):
    """Reshape lhs to rhs's shape (parity: reshape_like op, incl. the
    partial-range form reshaping lhs[lhs_begin:lhs_end] dims to
    rhs[rhs_begin:rhs_end] dims)."""
    lhs, rhs = _as_nd(lhs), _as_nd(rhs)

    partial = any(v is not None for v in
                  (lhs_begin, lhs_end, rhs_begin, rhs_end))

    def f(a, b):
        if not partial:
            return jnp.reshape(a, b.shape)
        lb = 0 if lhs_begin is None else lhs_begin
        le = a.ndim if lhs_end is None else lhs_end
        rb = 0 if rhs_begin is None else rhs_begin
        re_ = b.ndim if rhs_end is None else rhs_end
        new_shape = a.shape[:lb] + b.shape[rb:re_] + a.shape[le:]
        return jnp.reshape(a, new_shape)

    return invoke("reshape_like", f, [lhs, rhs])


@_export
def broadcast_axis(data, axis=(), size=()):
    data = _as_nd(data)
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    sizes = (size,) if isinstance(size, int) else tuple(size)
    tgt = list(data.shape)
    for a, s in zip(axes, sizes):
        tgt[a] = s
    return invoke("broadcast_axis",
                  lambda x: jnp.broadcast_to(x, tuple(tgt)), [data])


@_export
def concat(*data, dim=1, **kw):
    if len(data) == 1 and isinstance(data[0], (list, tuple)):
        data = tuple(data[0])
    nds = [_as_nd(d) for d in data]
    return invoke("concat", lambda *xs: jnp.concatenate(xs, axis=dim),
                  list(nds))


Concat = concat
__all__.append("Concat")


@_export
def stack(*data, axis=0, **kw):
    if len(data) == 1 and isinstance(data[0], (list, tuple)):
        data = tuple(data[0])
    nds = [_as_nd(d) for d in data]
    return invoke("stack", lambda *xs: jnp.stack(xs, axis=axis), list(nds))


@_export
def split(data, num_outputs=None, axis=1, squeeze_axis=False):
    data = _as_nd(data)

    def f(x):
        parts = jnp.split(x, num_outputs, axis=axis)
        if squeeze_axis:
            parts = [jnp.squeeze(p, axis=axis) for p in parts]
        return tuple(parts)

    return invoke("split", f, [data])


SliceChannel = split
__all__.append("SliceChannel")


@_export
def slice(data, begin, end, step=None):
    data = _as_nd(data)
    begin = tuple(begin); end = tuple(end)
    step = tuple(step) if step is not None else (1,) * len(begin)
    idx = tuple(builtins.slice(b, e, s) for b, e, s in zip(begin, end, step))
    return invoke("slice", lambda x: x[idx], [data])


@_export
def slice_axis(data, axis, begin, end):
    data = _as_nd(data)
    def f(x):
        idx = [builtins.slice(None)] * x.ndim
        e = end if end is not None else x.shape[axis]
        idx[axis] = builtins.slice(begin, e)
        return x[tuple(idx)]
    return invoke("slice_axis", f, [data])


@_export
def slice_like(data, shape_like, axes=None):
    data, shape_like = _as_nd(data), _as_nd(shape_like)
    tgt = shape_like.shape

    def f(x, y):
        idx = [builtins.slice(None)] * x.ndim
        axs = axes if axes is not None else range(len(tgt))
        for a in axs:
            idx[a] = builtins.slice(0, tgt[a])
        return x[tuple(idx)]

    return invoke("slice_like", f, [data, shape_like])


@_export
def take(a, indices, axis=0, mode="clip"):
    a, indices = _as_nd(a), _as_nd(indices)

    def f(x, idx):
        return jnp.take(x, idx.astype(jnp.int32), axis=axis,
                        mode="wrap" if mode == "wrap" else "clip")

    return invoke("take", f, [a, indices])


@_export
def pick(data, index, axis=-1, keepdims=False, mode="clip"):
    data, index = _as_nd(data), _as_nd(index)

    def f(x, idx):
        out = jnp.take_along_axis(
            x, jnp.expand_dims(idx.astype(jnp.int32), axis), axis=axis)
        return out if keepdims else jnp.squeeze(out, axis=axis)

    return invoke("pick", f, [data, index])


@_export
def gather_nd(data, indices):
    data, indices = _as_nd(data), _as_nd(indices)

    def f(x, idx):
        idx = idx.astype(jnp.int32)
        return x[tuple(idx[i] for i in range(idx.shape[0]))]

    return invoke("gather_nd", f, [data, indices])


@_export
def scatter_nd(data, indices, shape):
    data, indices = _as_nd(data), _as_nd(indices)

    def f(d, idx):
        idx = idx.astype(jnp.int32)
        z = jnp.zeros(tuple(shape), dtype=d.dtype)
        return z.at[tuple(idx[i] for i in range(idx.shape[0]))].add(d)

    return invoke("scatter_nd", f, [data, indices])


@_export
def one_hot(indices, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    indices = _as_nd(indices)
    dt = jnp.dtype(_base.canonical_dtype(dtype))

    def f(idx):
        oh = jax.nn.one_hot(idx.astype(jnp.int32), depth, dtype=dt)
        return oh * (on_value - off_value) + off_value

    return invoke("one_hot", f, [indices], differentiable=False)


@_export
def tile(data, reps):
    data = _as_nd(data)
    return invoke("tile", lambda x: jnp.tile(x, reps), [data])


@_export
def repeat(data, repeats, axis=None):
    data = _as_nd(data)
    return invoke("repeat", lambda x: jnp.repeat(x, repeats, axis=axis),
                  [data])


@_export
def flip(data, axis):
    data = _as_nd(data)
    return invoke("flip", lambda x: jnp.flip(x, axis=axis), [data])


reverse = flip
__all__.append("reverse")


@_export
def pad(data, mode="constant", pad_width=None, constant_value=0.0):
    data = _as_nd(data)
    pw = tuple(pad_width)
    pairs = tuple((pw[2 * i], pw[2 * i + 1]) for i in range(len(pw) // 2))
    jmode = {"constant": "constant", "edge": "edge",
             "reflect": "reflect"}[mode]

    def f(x):
        if jmode == "constant":
            return jnp.pad(x, pairs, mode=jmode,
                           constant_values=constant_value)
        return jnp.pad(x, pairs, mode=jmode)

    return invoke("pad", f, [data])


Pad = pad
__all__.append("Pad")


@_export
def arange_like(data, start=0.0, step=1.0, axis=None):
    data = _as_nd(data)

    def f(x):
        if axis is None:
            n = x.size
            return (start + step * jnp.arange(n, dtype=x.dtype)).reshape(x.shape)
        n = x.shape[axis]
        return start + step * jnp.arange(n, dtype=x.dtype)

    return invoke("arange_like", f, [data], differentiable=False)


@_export
def shape_array(data):
    data = _as_nd(data)
    return from_jax(jnp.asarray(data.shape, dtype=jnp.int64), ctx=data.context)


@_export
def size_array(data):
    data = _as_nd(data)
    return from_jax(jnp.asarray([data.size], dtype=jnp.int64),
                    ctx=data.context)


# ----------------------------------------------------- indexing for NDArray

def _getitem(data, key):
    return invoke("getitem", lambda x: x[key], [data])


def _setitem(data, key, value):
    r = invoke("setitem",
               lambda x, v: x.at[key].set(v.astype(x.dtype)), [data, value])
    data._rebind(r.jax, node=r._node)


def _setitem_full(data, value):
    r = invoke("setitem_full",
               lambda x, v: jnp.broadcast_to(v.astype(x.dtype), x.shape),
               [data, value])
    data._rebind(r.jax, node=r._node)


# ------------------------------------------------------------ activations &
# softmax family

@_export
def softmax(data, axis=-1, length=None, temperature=None, use_length=False):
    data = _as_nd(data)
    t = temperature or 1.0
    if length is not None:
        length = _as_nd(length)

        def f(x, ln):
            # mask positions >= length along `axis` (SequenceMask'd softmax,
            # parity: src/operator/nn/softmax*.h length path)
            n = x.shape[axis]
            ar = jnp.arange(n)
            shape = [1] * x.ndim
            shape[axis] = n
            ar = ar.reshape(shape)
            ln_b = jnp.expand_dims(ln.astype(jnp.int32), axis)
            mask = ar < ln_b
            neg = jnp.finfo(x.dtype).min
            return jax.nn.softmax(jnp.where(mask, x / t, neg), axis=axis) * mask

        return invoke("softmax", f, [data, length])
    return invoke("softmax", lambda x: jax.nn.softmax(x / t, axis=axis),
                  [data])


@_export
def log_softmax(data, axis=-1, temperature=None):
    data = _as_nd(data)
    t = temperature or 1.0
    return invoke("log_softmax",
                  lambda x: jax.nn.log_softmax(x / t, axis=axis), [data])


@_export
def logsumexp(data, axis=-1, keepdims=False):
    """log(sum(exp(x))) along `axis`, computed stably in f32 (the reduction
    that lets losses avoid materializing a full log_softmax)."""
    data = _as_nd(data)

    def f(x):
        r = jax.scipy.special.logsumexp(
            x.astype(jnp.float32), axis=axis, keepdims=keepdims)
        return r

    return invoke("logsumexp", f, [data])


@_export
def softmax_cross_entropy(data, label):
    data, label = _as_nd(data), _as_nd(label)

    def f(x, y):
        ls = jax.nn.log_softmax(x, axis=-1)
        picked = jnp.take_along_axis(
            ls, y.astype(jnp.int32)[:, None], axis=-1)
        return -jnp.sum(picked)

    return invoke("softmax_cross_entropy", f, [data, label])


ACTIVATION_FNS = {
    "relu": jax.nn.relu, "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh, "softrelu": jax.nn.softplus,
    "softsign": jax.nn.soft_sign, "log_sigmoid": jax.nn.log_sigmoid,
    "mish": lambda x: x * jnp.tanh(jax.nn.softplus(x)),
    "gelu": jax.nn.gelu, "silu": jax.nn.silu}


@_export
def Activation(data, act_type="relu", **kw):
    data = _as_nd(data)
    return invoke(f"activation_{act_type}", ACTIVATION_FNS[act_type],
                  [data])


@_export
def LeakyReLU(data, gamma=None, act_type="leaky", slope=0.25,
              lower_bound=0.125, upper_bound=0.334, **kw):
    data = _as_nd(data)
    if act_type == "leaky":
        return invoke("leaky_relu",
                      lambda x: jax.nn.leaky_relu(x, negative_slope=slope),
                      [data])
    if act_type == "elu":
        return invoke("elu", lambda x: jax.nn.elu(x, alpha=slope), [data])
    if act_type == "selu":
        return invoke("selu", jax.nn.selu, [data])
    if act_type == "gelu":
        return invoke("gelu", functools.partial(jax.nn.gelu, approximate=False), [data])
    if act_type == "prelu":
        g = _as_nd(gamma)
        return invoke("prelu",
                      lambda x, a: jnp.where(x >= 0, x, a * x), [data, g])
    if act_type == "rrelu":
        mid = (lower_bound + upper_bound) / 2.0
        if _base.is_training():
            key = _random.next_key(data.context)
            def f(x):
                s = jax.random.uniform(key, x.shape, minval=lower_bound,
                                       maxval=upper_bound, dtype=x.dtype)
                return jnp.where(x >= 0, x, s * x)
            return invoke("rrelu", f, [data])
        return invoke("rrelu",
                      lambda x: jnp.where(x >= 0, x, mid * x), [data])
    raise ValueError(f"unknown LeakyReLU act_type {act_type}")


# ------------------------------------------------------------- neural ops

@_export
def FullyConnected(data, weight, bias=None, num_hidden=None,
                   no_bias=False, flatten=True, **kw):
    """Parity: src/operator/nn/fully_connected.cc. weight is (out, in)."""
    nds = [_as_nd(data), _as_nd(weight)]
    has_bias = bias is not None and not no_bias
    if has_bias:
        nds.append(_as_nd(bias))

    def f(x, w, *b):
        if flatten and x.ndim > 2:
            x = x.reshape(x.shape[0], -1)
        y = jnp.matmul(x, w.T)
        if b:
            y = y + b[0]
        return y

    return invoke("FullyConnected", f, nds)


@_export
def Embedding(data, weight, input_dim=None, output_dim=None,
              dtype="float32", sparse_grad=False, **kw):
    data, weight = _as_nd(data), _as_nd(weight)

    def f(idx, w):
        return jnp.take(w, idx.astype(jnp.int32), axis=0, mode="clip")

    if sparse_grad:
        r = _embedding_sparse_grad(data, weight, f)
        if r is not None:
            return r
    return invoke("Embedding", f, [data, weight])


def _embedding_sparse_grad(data, weight, f):
    """Eager sparse_grad=True lookup: the weight cotangent is emitted as a
    compact RowSparse structure (unique rows + segment-sum) instead of a
    dense scatter-add (parity: Embedding's sparse_grad path, SURVEY §2.3
    `src/operator/tensor/indexing_op.*`).  Returns None — falling back to
    the dense path — inside traces (whole-step vjp already yields dense
    grads there) or when the weight is not a gradient leaf."""
    from ..autograd.tape import LeafNode
    if not _base.is_recording():
        return None
    wnode = node_of(weight)
    if not isinstance(wnode, LeafNode):
        return None
    idx_val, w_val = data.jax, weight.jax
    if isinstance(idx_val, jax.core.Tracer) or \
            isinstance(w_val, jax.core.Tracer):
        return None
    out = f(idx_val, w_val)
    res = NDArray(out, ctx=weight.context)
    n_rows, row_shape = w_val.shape[0], w_val.shape[1:]
    flat_idx = onp.clip(onp.asarray(idx_val).astype("int64").reshape(-1),
                        0, n_rows - 1)
    uniq, inv = onp.unique(flat_idx, return_inverse=True)
    inv_j = jnp.asarray(inv, jnp.int32)
    uniq_j = jnp.asarray(uniq, jnp.int32)

    def vjp_fn(cot):
        from .sparse import _RowSparseCot
        rows = jax.ops.segment_sum(
            cot.reshape((-1,) + row_shape), inv_j, num_segments=len(uniq))
        return (None, _RowSparseCot(rows, uniq_j, w_val.shape))

    node = OpNode(
        vjp_fn, [None, wnode], 1, name="Embedding(sparse_grad)",
        out_avals=[jax.ShapeDtypeStruct(out.shape, out.dtype)])
    res._node = OutRef(node, 0)
    if _invoke_hooks:
        for h in tuple(_invoke_hooks):
            h("Embedding", [res])
    return res


_CHANNELS_LAST_LAYOUTS = ("NWC", "NHWC", "NDHWC")


def _conv_dim_numbers(ndim, layout=None):
    """MXNet layout string → lax dimension numbers.  Weights stay in the
    upstream (O, I, kH, kW) layout for BOTH data layouts so checkpoints
    are layout-portable; XLA relaids them internally."""
    if layout in (None, "NCW", "NCHW", "NCDHW"):
        if layout is not None and len(layout) != ndim:
            raise _base.MXNetError(
                f"conv layout {layout!r} expects {len(layout)}-d input, "
                f"got {ndim}-d")
        if ndim == 3:
            return ("NCH", "OIH", "NCH")
        if ndim == 4:
            return ("NCHW", "OIHW", "NCHW")
        return ("NCDHW", "OIDHW", "NCDHW")
    if layout == "NWC" and ndim == 3:
        return ("NHC", "OIH", "NHC")
    if layout == "NHWC" and ndim == 4:
        # feature dim last = TPU lane dim: the conv needs no edge
        # transposes (src/operator/nn/convolution.cc accepts NHWC too)
        return ("NHWC", "OIHW", "NHWC")
    if layout == "NDHWC" and ndim == 5:
        return ("NDHWC", "OIDHW", "NDHWC")
    raise _base.MXNetError(f"unsupported conv layout {layout!r} for "
                           f"{ndim}-d input")


@_export
def Convolution(data, weight, bias=None, kernel=None, stride=None,
                dilate=None, pad=None, num_filter=None, num_group=1,
                no_bias=False, layout=None, **kw):
    """Parity: src/operator/nn/convolution.cc — NCHW default or NHWC via
    ``layout`` (TPU-preferred: channels on the lane dim), (O,I,kH,kW)
    weights either way.  Lowers to lax.conv_general_dilated → MXU."""
    data = _as_nd(data)
    weight = _as_nd(weight)
    nds = [data, weight]
    has_bias = bias is not None and not no_bias
    if has_bias:
        nds.append(_as_nd(bias))
    nd_spatial = data.ndim - 2
    stride = tuple(stride) if stride else (1,) * nd_spatial
    dilate = tuple(dilate) if dilate else (1,) * nd_spatial
    pad_ = tuple(pad) if pad else (0,) * nd_spatial
    dn = _conv_dim_numbers(data.ndim, layout)
    channels_last = layout in _CHANNELS_LAST_LAYOUTS

    def f(x, w, *b):
        # no preferred_element_type: the MXU accumulates bf16 convs in f32
        # internally already, and lax's conv transpose-rhs rule rejects
        # mixed (bf16 operand, f32 cotangent) pairs it would produce
        y = lax.conv_general_dilated(
            x, w, window_strides=stride,
            padding=tuple((p, p) for p in pad_),
            rhs_dilation=dilate, dimension_numbers=dn,
            feature_group_count=num_group)
        if b:
            bshape = ((1,) + (1,) * nd_spatial + (-1,)) if channels_last \
                else ((1, -1) + (1,) * nd_spatial)
            y = y + b[0].reshape(bshape)
        return y

    return invoke("Convolution", f, nds)


@_export
def Deconvolution(data, weight, bias=None, kernel=None, stride=None,
                  dilate=None, pad=None, adj=None, num_filter=None,
                  num_group=1, no_bias=True, layout=None, **kw):
    data, weight = _as_nd(data), _as_nd(weight)
    _conv_dim_numbers(data.ndim, layout)   # validate the layout string
    if layout in _CHANNELS_LAST_LAYOUTS:
        raise _base.MXNetError(
            "channels-last layout is not supported for Deconvolution "
            "(runs NCHW)")
    nds = [data, weight]
    has_bias = bias is not None and not no_bias
    if has_bias:
        nds.append(_as_nd(bias))
    nd_spatial = data.ndim - 2
    stride = tuple(stride) if stride else (1,) * nd_spatial
    dilate = tuple(dilate) if dilate else (1,) * nd_spatial
    pad_ = tuple(pad) if pad else (0,) * nd_spatial
    kernel = tuple(kernel)
    dn = _conv_dim_numbers(data.ndim)

    def f(x, w, *b):
        pads = []
        for i in range(nd_spatial):
            k = (kernel[i] - 1) * dilate[i]
            pads.append((k - pad_[i], k - pad_[i]))
        # weight is (Cin, Cout/g, k...); grouped transpose-conv kernel must
        # be (Cout, Cin/g, k...): per-group swap of the io axes
        cin = w.shape[0]
        cout_g = w.shape[1]
        spatial = w.shape[2:]
        wg = w.reshape((num_group, cin // num_group, cout_g) + spatial)
        wg = jnp.swapaxes(wg, 1, 2)
        wt = wg.reshape((num_group * cout_g, cin // num_group) + spatial)
        wt = jnp.flip(wt, axis=tuple(range(2, 2 + nd_spatial)))
        y = lax.conv_general_dilated(
            x, wt,
            window_strides=(1,) * nd_spatial, padding=tuple(pads),
            lhs_dilation=stride, rhs_dilation=dilate,
            dimension_numbers=dn, feature_group_count=num_group)
        if b:
            bshape = (1, -1) + (1,) * nd_spatial
            y = y + b[0].reshape(bshape)
        return y

    return invoke("Deconvolution", f, nds)


@_export
def Pooling(data, kernel=None, pool_type="max", global_pool=False,
            stride=None, pad=None, pooling_convention="valid",
            count_include_pad=True, layout=None, **kw):
    """Parity: src/operator/nn/pooling.cc (max/avg/sum/lp); NCHW default
    or channels-last via ``layout`` (NWC/NHWC/NDHWC)."""
    data = _as_nd(data)
    nd_spatial = data.ndim - 2
    _LAYOUT_NDIM = {"NCW": 3, "NWC": 3, "NCHW": 4, "NHWC": 4,
                    "NCDHW": 5, "NDHWC": 5}
    if layout is not None:
        if layout not in _LAYOUT_NDIM:
            raise _base.MXNetError(f"unsupported pooling layout {layout!r}")
        if _LAYOUT_NDIM[layout] != data.ndim:
            raise _base.MXNetError(
                f"pooling layout {layout!r} expects "
                f"{_LAYOUT_NDIM[layout]}-d input, got {data.ndim}-d")
    channels_last = layout in _CHANNELS_LAST_LAYOUTS
    sp0 = 1 if channels_last else 2          # first spatial axis

    def f(x):
        if global_pool:
            axes = tuple(range(sp0, sp0 + nd_spatial))
            if pool_type == "max":
                return jnp.max(x, axis=axes, keepdims=True)
            return jnp.mean(x, axis=axes, keepdims=True)
        k = tuple(kernel)
        s = tuple(stride) if stride else k
        p = tuple(pad) if pad else (0,) * nd_spatial

        def lay(spatial, fill):
            sp = list(spatial)
            return ((fill, *sp, fill) if channels_last
                    else (fill, fill, *sp))

        window = lay(k, 1)
        strides = lay(s, 1)
        if pooling_convention == "full":
            # ceil-mode: pad upper side enough for a final partial window
            sp_pads = []
            for i in range(nd_spatial):
                in_sz = x.shape[sp0 + i] + 2 * p[i]
                out_sz = int(math.ceil((in_sz - k[i]) / s[i])) + 1
                need = (out_sz - 1) * s[i] + k[i] - in_sz
                sp_pads.append((p[i], p[i] + builtins.max(need, 0)))
        else:
            sp_pads = [(pi, pi) for pi in p]
        pads = tuple(lay(sp_pads, (0, 0)))
        if pool_type == "max":
            init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
                jnp.iinfo(x.dtype).min
            return lax.reduce_window(x, init, lax.max, window, strides, pads)
        if pool_type in ("avg", "sum"):
            summed = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
            if pool_type == "sum":
                return summed
            if count_include_pad:
                denom = 1
                for ki in k:
                    denom *= ki
                return summed / denom
            ones = jnp.ones_like(x)
            counts = lax.reduce_window(ones, 0.0, lax.add, window, strides,
                                       pads)
            return summed / counts
        if pool_type == "lp":
            pval = kw.get("p_value", 2)
            summed = lax.reduce_window(jnp.abs(x) ** pval, 0.0, lax.add,
                                       window, strides, pads)
            return summed ** (1.0 / pval)
        raise ValueError(f"unknown pool_type {pool_type}")

    return invoke("Pooling", f, [data])


@_export
def BatchNorm(data, gamma, beta, moving_mean, moving_var, eps=1e-5,
              momentum=0.9, fix_gamma=False, use_global_stats=False,
              output_mean_var=False, axis=1, **kw):
    """Parity: src/operator/nn/batch_norm.cc.

    Functional: returns (out, batch_mean, batch_var); the Gluon layer updates
    the moving stats (MXNet mutates aux states inside the op; we keep the op
    pure for XLA and move the mutation to the layer).
    """
    nds = [_as_nd(x) for x in (data, gamma, beta, moving_mean, moving_var)]
    training = _base.is_training() and not use_global_stats

    def f(x, g, b, mmean, mvar):
        ax = axis % x.ndim          # canonicalize: axis=-1 (NHWC) must
        shape = [1] * x.ndim        # exclude the LAST dim from the stat
        shape[ax] = x.shape[ax]     # reduction, not match nothing
        g_ = jnp.ones_like(g) if fix_gamma else g
        if training:
            axes = tuple(i for i in range(x.ndim) if i != ax)
            mean = jnp.mean(x, axis=axes)
            var = jnp.var(x, axis=axes)
        else:
            mean, var = mmean, mvar
        inv = lax.rsqrt(var + eps).reshape(shape)
        out = (x - mean.reshape(shape)) * inv * g_.reshape(shape) \
            + b.reshape(shape)
        return out, mean, var

    out, mean, var = invoke("BatchNorm", f, nds)
    if output_mean_var:
        return out, mean, var
    return (out, mean, var) if kw.get("_internal_stats") else out


@_export
def LayerNorm(data, gamma, beta, axis=-1, eps=1e-5, **kw):
    nds = [_as_nd(x) for x in (data, gamma, beta)]

    def f(x, g, b):
        mean = jnp.mean(x, axis=axis, keepdims=True)
        var = jnp.var(x, axis=axis, keepdims=True)
        shape = [1] * x.ndim
        shape[axis] = x.shape[axis]
        return (x - mean) * lax.rsqrt(var + eps) * g.reshape(shape) \
            + b.reshape(shape)

    return invoke("LayerNorm", f, nds)


@_export
def GroupNorm(data, gamma, beta, num_groups=1, eps=1e-5, **kw):
    nds = [_as_nd(x) for x in (data, gamma, beta)]

    def f(x, g, b):
        n, c = x.shape[0], x.shape[1]
        spatial = x.shape[2:]
        xg = x.reshape((n, num_groups, c // num_groups) + spatial)
        axes = tuple(range(2, xg.ndim))
        mean = jnp.mean(xg, axis=axes, keepdims=True)
        var = jnp.var(xg, axis=axes, keepdims=True)
        xn = ((xg - mean) * lax.rsqrt(var + eps)).reshape(x.shape)
        shape = (1, c) + (1,) * len(spatial)
        return xn * g.reshape(shape) + b.reshape(shape)

    return invoke("GroupNorm", f, nds)


@_export
def InstanceNorm(data, gamma, beta, eps=1e-3, **kw):
    nds = [_as_nd(x) for x in (data, gamma, beta)]

    def f(x, g, b):
        axes = tuple(range(2, x.ndim))
        mean = jnp.mean(x, axis=axes, keepdims=True)
        var = jnp.var(x, axis=axes, keepdims=True)
        shape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
        return (x - mean) * lax.rsqrt(var + eps) * g.reshape(shape) \
            + b.reshape(shape)

    return invoke("InstanceNorm", f, nds)


@_export
def L2Normalization(data, eps=1e-10, mode="instance"):
    data = _as_nd(data)

    def f(x):
        if mode == "instance":
            axes = tuple(range(1, x.ndim))
        elif mode == "channel":
            axes = (1,)
        else:  # spatial
            axes = tuple(range(2, x.ndim))
        nrm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=True) + eps)
        return x / nrm

    return invoke("L2Normalization", f, [data])


@_export
def Dropout(data, p=0.5, mode="training", axes=(), cudnn_off=False, **kw):
    data = _as_nd(data)
    if not _base.is_training() and mode != "always":
        return invoke("dropout_id", lambda x: x, [data])
    if p <= 0:
        return invoke("dropout_id", lambda x: x, [data])
    key = _random.next_key(data.context)

    def f(x):
        shape = list(x.shape)
        for a in axes:
            shape[a] = 1  # broadcast dropout over these axes
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        return jnp.where(keep, x / (1.0 - p), jnp.zeros_like(x))

    return invoke("Dropout", f, [data])


@_export
def SequenceMask(data, sequence_length=None, use_sequence_length=False,
                 value=0.0, axis=0):
    data = _as_nd(data)
    if not use_sequence_length or sequence_length is None:
        return invoke("seqmask_id", lambda x: x, [data])
    sl = _as_nd(sequence_length)

    def f(x, ln):
        n = x.shape[axis]
        ar = jnp.arange(n)
        shape = [1] * x.ndim
        shape[axis] = n
        ar = ar.reshape(shape)
        batch_axis = 1 if axis == 0 else 0
        lshape = [1] * x.ndim
        lshape[batch_axis] = x.shape[batch_axis]
        mask = ar < ln.astype(jnp.int32).reshape(lshape)
        return jnp.where(mask, x, jnp.full_like(x, value))

    return invoke("SequenceMask", f, [data, sl])


@_export
def SequenceLast(data, sequence_length=None, use_sequence_length=False,
                 axis=0):
    data = _as_nd(data)
    if not use_sequence_length or sequence_length is None:
        def f(x):
            idx = [builtins.slice(None)] * x.ndim
            idx[axis] = -1
            return x[tuple(idx)]
        return invoke("SequenceLast", f, [data])
    sl = _as_nd(sequence_length)

    def f(x, ln):
        idx = (ln.astype(jnp.int32) - 1)
        xm = jnp.moveaxis(x, axis, 0)
        return jnp.take_along_axis(
            xm, idx.reshape((1, -1) + (1,) * (xm.ndim - 2)), axis=0)[0]

    return invoke("SequenceLast", f, [data, sl])


@_export
def SequenceReverse(data, sequence_length=None, use_sequence_length=False,
                    axis=0):
    data = _as_nd(data)
    if not use_sequence_length or sequence_length is None:
        return invoke("SequenceReverse",
                      lambda x: jnp.flip(x, axis=axis), [data])
    sl = _as_nd(sequence_length)

    def f(x, ln):
        t = x.shape[axis]
        xm = jnp.moveaxis(x, axis, 0)  # (T, B, ...)
        ar = jnp.arange(t)[:, None]
        ln_i = ln.astype(jnp.int32)[None, :]
        src = jnp.where(ar < ln_i, ln_i - 1 - ar, ar)
        out = jnp.take_along_axis(
            xm, src.reshape(src.shape + (1,) * (xm.ndim - 2)), axis=0)
        return jnp.moveaxis(out, 0, axis)

    return invoke("SequenceReverse", f, [data, sl])


# ---------------------------------------------------------------- sampling

def _sample_op(name, sampler):
    def op(*shape_args, shape=None, dtype="float32", ctx=None, out=None,
           **params):
        ctx = ctx or current_context()
        dt = jnp.dtype(_base.canonical_dtype(dtype))
        if shape is None:
            shape = ()
        if isinstance(shape, int):
            shape = (shape,)
        key = _random.next_key(ctx)
        val = sampler(key, tuple(shape), dt, **params)
        r = NDArray(val, ctx=ctx)
        if out is not None:
            out._rebind(r.jax)
            return out
        return r
    op.__name__ = name
    return _export(op)


random_uniform = _sample_op(
    "random_uniform",
    lambda key, shape, dt, low=0.0, high=1.0, **kw:
    jax.random.uniform(key, shape, dtype=dt, minval=low, maxval=high))
random_normal = _sample_op(
    "random_normal",
    lambda key, shape, dt, loc=0.0, scale=1.0, **kw:
    loc + scale * jax.random.normal(key, shape, dtype=dt))
random_gamma = _sample_op(
    "random_gamma",
    lambda key, shape, dt, alpha=1.0, beta=1.0, **kw:
    beta * jax.random.gamma(key, alpha, shape, dtype=dt))
random_exponential = _sample_op(
    "random_exponential",
    lambda key, shape, dt, lam=1.0, **kw:
    jax.random.exponential(key, shape, dtype=dt) / lam)
random_poisson = _sample_op(
    "random_poisson",
    lambda key, shape, dt, lam=1.0, **kw:
    jax.random.poisson(key, lam, shape).astype(dt))
random_randint = _sample_op(
    "random_randint",
    lambda key, shape, dt, low=0, high=2, **kw:
    jax.random.randint(key, shape, low, high).astype(dt))

normal = random_normal
uniform = random_uniform
__all__ += ["normal", "uniform"]


@_export
def random_bernoulli(p=0.5, shape=(), dtype="float32", ctx=None):
    ctx = ctx or current_context()
    key = _random.next_key(ctx)
    dt = jnp.dtype(_base.canonical_dtype(dtype))
    return NDArray(jax.random.bernoulli(key, p, shape).astype(dt), ctx=ctx)


@_export
def sample_multinomial(data, shape=1, get_prob=False, dtype="int32"):
    """Parity: src/operator/random/sample_op (multinomial).  `shape` is the
    per-distribution sample shape; with get_prob=True also returns the
    log-likelihood of each draw (policy-gradient idiom)."""
    data = _as_nd(data)
    key = _random.next_key(data.context)
    sample_shape = (shape,) if isinstance(shape, int) else tuple(shape)
    n = int(onp.prod(sample_shape)) if sample_shape else 1
    scalar = shape == 1
    dt = jnp.dtype(_base.canonical_dtype(dtype))

    def f(p):
        logits = jnp.log(jnp.maximum(p, 1e-37))
        if p.ndim == 1:
            s = jax.random.categorical(key, logits, shape=(n,))
            s = s[0] if scalar else s.reshape(sample_shape)
            logp = jnp.take(jax.nn.log_softmax(logits), s)
        else:
            s = jax.random.categorical(key, logits[:, None, :], axis=-1,
                                       shape=(p.shape[0], n))
            ls = jax.nn.log_softmax(logits, axis=-1)
            logp = jnp.take_along_axis(ls, s, axis=-1)
            if scalar:
                s, logp = s[:, 0], logp[:, 0]
            else:
                s = s.reshape((p.shape[0],) + sample_shape)
                logp = logp.reshape((p.shape[0],) + sample_shape)
        if get_prob:
            return s.astype(dt), logp
        return s.astype(dt)

    return invoke("sample_multinomial", f, [data], differentiable=False)


@_export
def shuffle(data):
    data = _as_nd(data)
    key = _random.next_key(data.context)
    return invoke("shuffle", lambda x: jax.random.permutation(key, x),
                  [data], differentiable=False)


# -------------------------------------------------------------- rnn helpers

@_export
def RNN(data, parameters, state, state_cell=None, state_size=None,
        num_layers=1, mode="lstm", bidirectional=False, p=0.0,
        state_outputs=True, projection_size=None, **kw):
    """Fused multi-layer RNN (parity: src/operator/rnn.cc).

    Layout: data (T, B, C).  Parameters packed flat exactly like MXNet/cuDNN:
    per layer/direction: [W_i2h, W_h2h] then all biases [b_i2h, b_h2h].
    Implemented with lax.scan over time — XLA fuses the gate matmuls; this is
    the TPU-idiomatic fused RNN.
    """
    from ..gluon.rnn._rnn_impl import rnn_forward  # lazy: avoids cycle
    return rnn_forward(data, parameters, state, state_cell, state_size,
                       num_layers, mode, bidirectional, p, state_outputs,
                       **kw)


# ----------------------------------------------------- misc / contrib ops

@_export
def smooth_l1(data, scalar=1.0):
    data = _as_nd(data)
    s2 = scalar * scalar

    def f(x):
        return jnp.where(jnp.abs(x) < 1.0 / s2, 0.5 * s2 * jnp.square(x),
                         jnp.abs(x) - 0.5 / s2)

    return invoke("smooth_l1", f, [data])


@_export
def SoftmaxOutput(data, label=None, grad_scale=1.0, ignore_label=-1,
                  use_ignore=False, normalization="null",
                  out_grad=False, **kw):
    """Classic 1.x softmax loss head (parity: src/operator/softmax_output.cc):
    forward = softmax(data); backward IGNORES the incoming gradient and
    emits (softmax - onehot(label)) * grad_scale, normalized per
    ``normalization`` ('null' | 'batch' | 'valid')."""
    data = _as_nd(data)
    if label is None:
        return softmax(data, axis=-1)
    label = _as_nd(label)

    @jax.custom_vjp
    def _softmax_output(x, y):
        return jax.nn.softmax(x, axis=-1)

    def _fwd(x, y):
        p = jax.nn.softmax(x, axis=-1)
        return p, (p, y)

    def _bwd(res, g):
        p, y = res
        yi = y.astype(jnp.int32)
        onehot = jax.nn.one_hot(yi, p.shape[-1], dtype=p.dtype)
        dx = (p - onehot) * grad_scale
        valid = None
        if use_ignore:
            valid = (yi != ignore_label)
            dx = dx * valid[..., None].astype(p.dtype)
        if normalization == "batch":
            dx = dx / p.shape[0]
        elif normalization == "valid":
            n = jnp.sum(valid) if valid is not None else \
                jnp.asarray(float(onp.prod(y.shape)), p.dtype)
            dx = dx / jnp.maximum(n, 1)
        return dx, jnp.zeros_like(y)

    _softmax_output.defvjp(_fwd, _bwd)
    return invoke("SoftmaxOutput", _softmax_output, [data, label])


@_export
def LinearRegressionOutput(data, label=None, grad_scale=1.0, **kw):
    """1.x L2 head (parity: regression_output.cc): forward = identity;
    backward = (data - label) * grad_scale."""
    data = _as_nd(data)
    if label is None:
        return data
    label = _as_nd(label)

    @jax.custom_vjp
    def _linreg(x, y):
        return x

    def _fwd(x, y):
        return x, (x, y)

    def _bwd(res, g):
        x, y = res
        return ((x - y.reshape(x.shape)) * grad_scale,
                jnp.zeros_like(y))

    _linreg.defvjp(_fwd, _bwd)
    return invoke("LinearRegressionOutput", _linreg, [data, label])


@_export
def MakeLoss(data, grad_scale=1.0, **kw):
    data = _as_nd(data)
    return invoke("make_loss", lambda x: x * grad_scale, [data])


@_export
def BlockGrad(data):
    return _as_nd(data).detach()


stop_gradient = BlockGrad
__all__.append("stop_gradient")


@_export
def interleaved_matmul_selfatt_qk(queries_keys_values, heads):
    """Parity: src/operator/contrib/transformer.cc (GluonNLP BERT path).

    qkv: (T, B, 3*E) interleaved per head: [q h0, k h0, v h0, q h1, ...].
    Returns (B*heads, T, T) scaled scores.
    """
    qkv = _as_nd(queries_keys_values)

    def f(x):
        t, b, e3 = x.shape
        hd = e3 // (3 * heads)
        xr = x.reshape(t, b, heads, 3, hd)
        q = xr[:, :, :, 0, :]
        k = xr[:, :, :, 1, :]
        q = jnp.transpose(q, (1, 2, 0, 3)).reshape(b * heads, t, hd)
        k = jnp.transpose(k, (1, 2, 0, 3)).reshape(b * heads, t, hd)
        scale = 1.0 / jnp.sqrt(jnp.asarray(hd, dtype=x.dtype))
        return jnp.matmul(q * scale, jnp.swapaxes(k, -1, -2))

    return invoke("interleaved_matmul_selfatt_qk", f, [qkv])


@_export
def interleaved_matmul_selfatt_valatt(queries_keys_values, attention, heads):
    qkv, att = _as_nd(queries_keys_values), _as_nd(attention)

    def f(x, a):
        t, b, e3 = x.shape
        hd = e3 // (3 * heads)
        xr = x.reshape(t, b, heads, 3, hd)
        v = jnp.transpose(xr[:, :, :, 2, :], (1, 2, 0, 3)) \
            .reshape(b * heads, t, hd)
        out = jnp.matmul(a, v)  # (B*H, T, hd)
        out = out.reshape(b, heads, t, hd)
        return jnp.transpose(out, (2, 0, 1, 3)).reshape(t, b, heads * hd)

    return invoke("interleaved_matmul_selfatt_valatt", f, [qkv, att])


@_export
def div_sqrt_dim(data):
    data = _as_nd(data)
    return invoke("div_sqrt_dim",
                  lambda x: x / jnp.sqrt(jnp.asarray(x.shape[-1],
                                                     dtype=x.dtype)),
                  [data])


@_export
def choose_element_0index(data, index):
    return pick(data, index, axis=-1)


@_export
def UpSampling(data, scale=2, sample_type="nearest", **kw):
    data = _as_nd(data)

    def f(x):
        n, c, h, w = x.shape
        if sample_type == "nearest":
            return jnp.repeat(jnp.repeat(x, scale, axis=2), scale, axis=3)
        return jax.image.resize(x, (n, c, h * scale, w * scale), "bilinear")

    return invoke("UpSampling", f, [data])


@_export
def add_n(*args, **kw):
    """Sum a list of arrays (parity: elemwise_sum/add_n)."""
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    nds = [_as_nd(a) for a in args]
    return invoke("add_n", lambda *xs: functools.reduce(jnp.add, xs), nds)


@_export
def diag(data, k=0, axis1=0, axis2=1):
    """Parity: mx.nd.diag — extract diagonals (>=2-D) or build a diagonal
    matrix (1-D)."""
    data = _as_nd(data)

    def f(x):
        if x.ndim == 1:
            return jnp.diag(x, k=k)
        return jnp.diagonal(x, offset=k, axis1=axis1, axis2=axis2)

    return invoke("diag", f, [data])


@_export
def unravel_index(data, shape):
    data = _as_nd(data)
    return invoke(
        "unravel_index",
        lambda i: jnp.stack(jnp.unravel_index(i.astype(jnp.int32),
                                              tuple(shape))),
        [data], differentiable=False)


@_export
def ravel_multi_index(data, shape):
    data = _as_nd(data)

    def f(m):
        idx = tuple(m[i].astype(jnp.int32) for i in range(m.shape[0]))
        return jnp.ravel_multi_index(idx, tuple(shape), mode="clip")

    return invoke("ravel_multi_index", f, [data], differentiable=False)


@_export
def hard_sigmoid(data, alpha=0.2, beta=0.5):
    data = _as_nd(data)
    return invoke("hard_sigmoid",
                  lambda x: jnp.clip(alpha * x + beta, 0.0, 1.0), [data])


@_export
def relu6(data):
    data = _as_nd(data)
    return invoke("relu6", lambda x: jnp.clip(x, 0.0, 6.0), [data])


@_export
def selu(data):
    data = _as_nd(data)
    return invoke("selu", jax.nn.selu, [data])


@_export
def gelu(data):
    data = _as_nd(data)
    return invoke("gelu",
                  functools.partial(jax.nn.gelu, approximate=False), [data])


@_export
def prelu(data, gamma):
    data, gamma = _as_nd(data), _as_nd(gamma)

    def f(x, g):
        gshape = [1] * x.ndim
        if x.ndim > 1:
            gshape[1] = -1
        return jnp.where(x >= 0, x, x * g.reshape(gshape))

    return invoke("prelu", f, [data, gamma])


random_negative_binomial = _sample_op(
    "random_negative_binomial",
    lambda key, shape, dt, k=1, p=1.0, **kw:
    jax.random.poisson(
        jax.random.fold_in(key, 1),
        jax.random.gamma(key, k, shape) * (1 - p) / builtins.max(p, 1e-12),
        shape).astype(dt))
random_generalized_negative_binomial = _sample_op(
    "random_generalized_negative_binomial",
    lambda key, shape, dt, mu=1.0, alpha=1.0, **kw:
    jax.random.poisson(
        jax.random.fold_in(key, 1),
        jax.random.gamma(key, 1.0 / builtins.max(alpha, 1e-12), shape)
        * (alpha * mu), shape).astype(dt))


def _param_sample_op(name, sampler):
    """Per-distribution sampling: parameter ARRAYS, one draw-set per row
    (parity: sample_uniform/sample_normal...)."""
    def op(*params, shape=(), dtype="float32", ctx=None, **kw):
        nds = [_as_nd(p) for p in params]
        dt = jnp.dtype(_base.canonical_dtype(dtype))
        sample_shape = (shape,) if isinstance(shape, int) else tuple(shape)
        key = _random.next_key(nds[0].context if nds else current_context())

        def f(*ps):
            full = ps[0].shape + sample_shape
            broad = [p.reshape(p.shape + (1,) * len(sample_shape))
                     for p in ps]
            return sampler(key, full, dt, *broad)

        return invoke(name, f, nds, differentiable=False)
    op.__name__ = name
    return _export(op)


sample_uniform = _param_sample_op(
    "sample_uniform",
    lambda key, full, dt, low, high:
    low + (high - low) * jax.random.uniform(key, full, dtype=dt))
sample_normal = _param_sample_op(
    "sample_normal",
    lambda key, full, dt, mu, sigma:
    mu + sigma * jax.random.normal(key, full, dtype=dt))
sample_gamma = _param_sample_op(
    "sample_gamma",
    lambda key, full, dt, alpha, beta:
    beta * jax.random.gamma(key, alpha, full, dtype=dt))
sample_exponential = _param_sample_op(
    "sample_exponential",
    lambda key, full, dt, lam:
    jax.random.exponential(key, full, dtype=dt) / lam)
sample_poisson = _param_sample_op(
    "sample_poisson",
    lambda key, full, dt, lam:
    jax.random.poisson(key, jnp.broadcast_to(lam, full), full).astype(dt))


@_export
def make_loss(data, **kw):
    return MakeLoss(data, **kw)


@_export
def ROIPooling(data, rois, pooled_size, spatial_scale, **kw):
    """Parity: src/operator/roi_pooling.cc — max-pool each ROI into a
    fixed (ph, pw) grid.  rois are (R, 5): [batch_idx, x1, y1, x2, y2]
    in image coords.  Coordinate rounding is half-away-from-zero and bin
    edges are floor/ceil of fractional boundaries (bins may overlap, and
    a narrow ROI can contribute one pixel to MANY bins).  A rectangle
    max is separable, so each ROI costs O((ph+pw)*C*H*W): ph masked row
    reductions then pw masked column reductions — exact for every
    overlap case."""
    data, rois = _as_nd(data), _as_nd(rois)
    ph, pw = pooled_size

    def f(x, r):
        n, c, h, w = x.shape
        ys = jnp.arange(h, dtype=jnp.float32)
        xs = jnp.arange(w, dtype=jnp.float32)

        def one(roi):
            b = roi[0].astype(jnp.int32)
            # C++ round: half away from zero (coords are non-negative)
            x1 = jnp.floor(roi[1] * spatial_scale + 0.5)
            y1 = jnp.floor(roi[2] * spatial_scale + 0.5)
            x2 = jnp.floor(roi[3] * spatial_scale + 0.5)
            y2 = jnp.floor(roi[4] * spatial_scale + 0.5)
            rw = jnp.maximum(x2 - x1 + 1.0, 1.0)
            rh = jnp.maximum(y2 - y1 + 1.0, 1.0)
            fm = x[b]                                  # (C, H, W)

            row_maxes = []
            for i in range(ph):
                sy = jnp.floor(y1 + i * rh / ph)
                ey = jnp.ceil(y1 + (i + 1) * rh / ph)
                m = (ys >= sy) & (ys < ey)
                row_maxes.append(
                    jnp.where(m[None, :, None], fm, -jnp.inf).max(axis=1))
            rowm = jnp.stack(row_maxes, axis=1)        # (C, ph, W)

            col_maxes = []
            for j in range(pw):
                sx = jnp.floor(x1 + j * rw / pw)
                ex = jnp.ceil(x1 + (j + 1) * rw / pw)
                m = (xs >= sx) & (xs < ex)
                col_maxes.append(
                    jnp.where(m[None, None, :], rowm, -jnp.inf).max(axis=2))
            out = jnp.stack(col_maxes, axis=2)         # (C, ph, pw)
            return jnp.where(jnp.isfinite(out), out, 0.0)

        return jax.vmap(one)(r)

    return invoke("ROIPooling", f, [data, rois])


@_export
def Crop(data, *like, offset=(0, 0), h_w=(0, 0), center_crop=False, **kw):
    """Parity: mx.nd.Crop (v1 symbol era) — crop data (N,C,H,W) to the
    spatial size of `like` (second input) or to `h_w`, at `offset` or
    centered."""
    data = _as_nd(data)
    nds = [data]
    if like:
        nds.append(_as_nd(like[0]))

    def f(x, *rest):
        th, tw = (rest[0].shape[2], rest[0].shape[3]) if rest else h_w
        if center_crop:
            y0 = (x.shape[2] - th) // 2
            x0 = (x.shape[3] - tw) // 2
        else:
            y0, x0 = offset
        return x[:, :, y0:y0 + th, x0:x0 + tw]

    return invoke("Crop", f, nds)


# ------------------------------------------------------- long-tail op sweep
# (VERDICT r2 missing #4: ops off the main model path that upstream scripts
# reach for — vision kernels, LRN-era layers, linalg, detection utilities.
# Parity: src/operator/{nn/lrn,contrib/*,tensor/la_op}*)


@_export
def LRN(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5, **kw):
    """Local response normalization across channels (parity: mx.nd.LRN,
    src/operator/nn/lrn.cc; the AlexNet-era layer)."""
    data = _as_nd(data)

    def f(x):
        sq = x * x
        half = nsize // 2
        # sum over a channel window via padded sliding window
        pad = [(0, 0)] * x.ndim
        pad[1] = (half, half)
        sqp = jnp.pad(sq, pad)
        acc = builtins.sum(
            jax.lax.slice_in_dim(sqp, i, i + x.shape[1], axis=1)
            for i in range(nsize))
        # upstream lrn-inl.h normalizes alpha by the window size
        return x / jnp.power(knorm + (alpha / nsize) * acc, beta)

    return invoke("LRN", f, [data])


@_export
def SoftmaxActivation(data, mode="instance", **kw):
    """Deprecated-but-used softmax layer (parity: mx.nd.SoftmaxActivation):
    mode='instance' softmaxes over all non-batch dims flattened;
    mode='channel' softmaxes over axis 1."""
    data = _as_nd(data)

    def f(x):
        if mode == "channel":
            return jax.nn.softmax(x, axis=1)
        flat = x.reshape(x.shape[0], -1)
        return jax.nn.softmax(flat, axis=-1).reshape(x.shape)

    return invoke("SoftmaxActivation", f, [data])


@_export
def depth_to_space(data, block_size, **kw):
    """(N, C*b*b, H, W) → (N, C, H*b, W*b) (parity: mx.nd.depth_to_space,
    DCR order like the upstream kernel)."""
    data = _as_nd(data)
    b = int(block_size)

    def f(x):
        n, c, h, w = x.shape
        x = x.reshape(n, b, b, c // (b * b), h, w)
        x = x.transpose(0, 3, 4, 1, 5, 2)
        return x.reshape(n, c // (b * b), h * b, w * b)

    return invoke("depth_to_space", f, [data])


@_export
def space_to_depth(data, block_size, **kw):
    """(N, C, H*b, W*b) → (N, C*b*b, H, W) (parity: mx.nd.space_to_depth)."""
    data = _as_nd(data)
    b = int(block_size)

    def f(x):
        n, c, h, w = x.shape
        x = x.reshape(n, c, h // b, b, w // b, b)
        x = x.transpose(0, 3, 5, 1, 2, 4)
        return x.reshape(n, c * b * b, h // b, w // b)

    return invoke("space_to_depth", f, [data])


@_export
def batch_take(a, indices, **kw):
    """Per-row element pick: out[i] = a[i, indices[i]] (parity:
    mx.nd.batch_take)."""
    a, indices = _as_nd(a), _as_nd(indices)

    def f(x, idx):
        return jnp.take_along_axis(
            x, idx.astype(jnp.int32).reshape(-1, 1), axis=1)[:, 0]

    return invoke("batch_take", f, [a, indices])


@_export
def cumsum(a, axis=None, dtype=None, **kw):
    """Parity: mx.np.cumsum exposed on the nd namespace too."""
    a = _as_nd(a)

    def f(x):
        y = jnp.cumsum(x.ravel() if axis is None else x, axis=0 if axis is
                       None else axis)
        return y.astype(_base.canonical_dtype(dtype)) if dtype else y

    return invoke("cumsum", f, [a])


@_export
def cumprod(a, axis=None, dtype=None, **kw):
    a = _as_nd(a)

    def f(x):
        y = jnp.cumprod(x.ravel() if axis is None else x, axis=0 if axis is
                        None else axis)
        return y.astype(_base.canonical_dtype(dtype)) if dtype else y

    return invoke("cumprod", f, [a])


@_export
def moments(data, axes=None, keepdims=False, **kw):
    """(mean, variance) over `axes` (parity: mx.nd.moments)."""
    data = _as_nd(data)
    ax = tuple(axes) if isinstance(axes, (list, tuple)) else axes

    def f(x):
        mk = jnp.mean(x, axis=ax, keepdims=True)
        v = jnp.mean((x - mk) ** 2, axis=ax, keepdims=keepdims)
        m = mk if keepdims else jnp.squeeze(
            mk, axis=ax if ax is not None
            else tuple(range(x.ndim)))
        return m, v

    return invoke("moments", f, [data], nout=2)


# ---- linalg long tail (parity: src/operator/tensor/la_op.cc) ----

@_export
def linalg_det(A, **kw):
    A = _as_nd(A)
    return invoke("linalg_det", jnp.linalg.det, [A])


@_export
def linalg_slogdet(A, **kw):
    A = _as_nd(A)

    def f(a):
        sign, logabs = jnp.linalg.slogdet(a)
        return sign, logabs

    return invoke("linalg_slogdet", f, [A], nout=2)


@_export
def linalg_inverse(A, **kw):
    A = _as_nd(A)
    return invoke("linalg_inverse", jnp.linalg.inv, [A])


@_export
def linalg_extractdiag(A, offset=0, **kw):
    A = _as_nd(A)
    return invoke("linalg_extractdiag",
                  lambda a: jnp.diagonal(a, offset=offset, axis1=-2,
                                         axis2=-1), [A])


@_export
def linalg_makediag(A, offset=0, **kw):
    A = _as_nd(A)

    def f(a):
        n = a.shape[-1] + builtins.abs(offset)
        out = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
        idx = jnp.arange(a.shape[-1])
        r = idx + builtins.max(-offset, 0)
        c = idx + builtins.max(offset, 0)
        return out.at[..., r, c].set(a)

    return invoke("linalg_makediag", f, [A])


# ---- spatial sampling (parity: src/operator/bilinear_sampler.cc,
#      grid_generator, spatial_transformer, contrib/roi_align) ----

def _bilinear_sample(fm, gx, gy):
    """Sample fm (C, H, W) at normalized grid coords gx/gy in [-1, 1]
    (Ho, Wo) with zero padding outside — the BilinearSampler contract."""
    c, h, w = fm.shape
    x = (gx + 1.0) * (w - 1) / 2.0
    y = (gy + 1.0) * (h - 1) / 2.0
    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    wx = x - x0
    wy = y - y0

    def at(yy, xx):
        inside = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
        yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
        v = fm[:, yc, xc]                      # (C, Ho, Wo)
        return jnp.where(inside[None], v, 0.0)

    return (at(y0, x0) * (1 - wx) * (1 - wy)
            + at(y0, x0 + 1) * wx * (1 - wy)
            + at(y0 + 1, x0) * (1 - wx) * wy
            + at(y0 + 1, x0 + 1) * wx * wy)


@_export
def BilinearSampler(data, grid, **kw):
    """Sample (N, C, H, W) at grid (N, 2, Ho, Wo) of normalized coords
    (parity: mx.nd.BilinearSampler — the STN sampling stage)."""
    data, grid = _as_nd(data), _as_nd(grid)

    def f(x, g):
        return jax.vmap(
            lambda fm, gg: _bilinear_sample(fm, gg[0], gg[1]))(x, g)

    return invoke("BilinearSampler", f, [data, grid])


@_export
def GridGenerator(data, transform_type="affine", target_shape=None, **kw):
    """Generate a sampling grid from 6-dof affine params (N, 6) or use
    direct flow (N, 2, H, W) (parity: mx.nd.GridGenerator)."""
    data = _as_nd(data)

    def f(t):
        if transform_type == "warp":
            n, _, h, w = t.shape
            xs, ys = jnp.meshgrid(jnp.arange(w, dtype=jnp.float32),
                                  jnp.arange(h, dtype=jnp.float32))
            gx = (xs[None] + t[:, 0]) * 2.0 / (w - 1) - 1.0
            gy = (ys[None] + t[:, 1]) * 2.0 / (h - 1) - 1.0
            return jnp.stack([gx, gy], axis=1)
        h, w = target_shape
        xs = jnp.linspace(-1.0, 1.0, w)
        ys = jnp.linspace(-1.0, 1.0, h)
        gx, gy = jnp.meshgrid(xs, ys)
        ones = jnp.ones_like(gx)
        src = jnp.stack([gx, gy, ones], axis=0).reshape(3, -1)  # (3, HW)
        theta = t.reshape(-1, 2, 3)
        out = jnp.einsum("nij,jk->nik", theta, src)             # (N, 2, HW)
        return out.reshape(-1, 2, h, w)

    return invoke("GridGenerator", f, [data])


@_export
def SpatialTransformer(data, loc, target_shape=None,
                       transform_type="affine",
                       sampler_type="bilinear", **kw):
    """STN: affine grid from `loc` then bilinear sampling (parity:
    mx.nd.SpatialTransformer)."""
    grid = GridGenerator(loc, transform_type=transform_type,
                         target_shape=target_shape)
    return BilinearSampler(data, grid)


# ---- detection utilities (parity: src/operator/contrib/bounding_box.cc,
#      roi_align.cc) ----

@_export
def box_iou(lhs, rhs, format="corner", **kw):
    """Pairwise IoU of (..., N, 4) x (..., M, 4) boxes (parity:
    mx.nd.contrib.box_iou)."""
    lhs, rhs = _as_nd(lhs), _as_nd(rhs)

    def corners(b):
        if format == "center":
            cx, cy, w, h = (b[..., 0], b[..., 1], b[..., 2], b[..., 3])
            return (cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2)
        return (b[..., 0], b[..., 1], b[..., 2], b[..., 3])

    def f(a, b):
        ca = jnp.stack(corners(a), axis=-1)
        cb = jnp.stack(corners(b), axis=-1)
        return _pairwise_iou(ca, cb)

    return invoke("box_iou", f, [lhs, rhs])


@_export
def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1,
            force_suppress=False, in_format="corner",
            out_format="corner", **kw):
    """Greedy non-max suppression with static shapes (parity:
    mx.nd.contrib.box_nms).  Suppressed rows become -1, preserving the
    upstream contract.  O(N^2) mask matrix + lax.scan over score order —
    static shapes keep XLA happy."""
    data = _as_nd(data)

    def f(x):
        shape = x.shape
        batched = x.ndim == 3
        xb = x if batched else x[None]

        def one(rows):
            scores = rows[:, score_index]
            boxes = rows[:, coord_start:coord_start + 4]
            if in_format == "center":
                cx, cy, w, h = (boxes[:, 0], boxes[:, 1], boxes[:, 2],
                                boxes[:, 3])
                boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2,
                                   cy + h / 2], axis=1)
            valid = scores > valid_thresh
            order = jnp.argsort(-jnp.where(valid, scores, -jnp.inf))
            iou = _pairwise_iou(boxes, boxes)
            same_cls = jnp.ones_like(iou, bool) if (
                force_suppress or id_index < 0) else (
                rows[:, id_index][:, None] == rows[:, id_index][None])
            sup_pair = (iou > overlap_thresh) & same_cls

            n = rows.shape[0]
            kmax = n if topk is None or topk < 0 else builtins.min(topk, n)

            def body(suppressed, oi):
                i = order[oi]
                # upstream truncates the CANDIDATE set at score rank k
                # before NMS — ranks beyond k are discarded outright
                ok = valid[i] & ~suppressed[i] & (oi < kmax)
                suppressed = jnp.where(
                    ok, suppressed | sup_pair[i], suppressed)
                suppressed = jnp.where(
                    ok, suppressed.at[i].set(False), suppressed)
                keep = jnp.where(ok, False, True)
                return suppressed, keep

            suppressed, dropped = jax.lax.scan(
                body, jnp.zeros((n,), bool), jnp.arange(n))
            # a row survives if valid, within the top-k candidates, not
            # suppressed by a kept row, and was itself kept
            kept_mask = jnp.zeros((n,), bool).at[order].set(~dropped)
            kept_mask = kept_mask & valid & ~suppressed
            # `boxes` is corner-format here regardless of in_format;
            # rewrite the coord columns only when the encoding changes
            out_rows = rows
            if out_format != in_format:
                if out_format == "corner":
                    b4 = boxes
                else:
                    b4 = jnp.stack(
                        [(boxes[:, 0] + boxes[:, 2]) / 2,
                         (boxes[:, 1] + boxes[:, 3]) / 2,
                         boxes[:, 2] - boxes[:, 0],
                         boxes[:, 3] - boxes[:, 1]], axis=1)
                out_rows = rows.at[
                    :, coord_start:coord_start + 4].set(b4)
            return jnp.where(kept_mask[:, None], out_rows,
                             jnp.full_like(rows, -1.0))

        out = jax.vmap(one)(xb)
        return out if batched else out.reshape(shape)

    return invoke("box_nms", f, [data])


@_export
def ROIAlign(data, rois, pooled_size=None, spatial_scale=1.0,
             sample_ratio=2, position_sensitive=False, **kw):
    """ROI Align with bilinear sampling (parity:
    mx.nd.contrib.ROIAlign, src/operator/contrib/roi_align.cc).

    Deviation: upstream's ``sample_ratio=-1`` adapts the per-bin sample
    count to each ROI's size, which needs dynamic shapes; here -1 maps
    to a STATIC 2x2 sample grid per bin (the common configured value)
    with a one-time warning."""
    data, rois = _as_nd(data), _as_nd(rois)
    ph, pw = pooled_size
    if sample_ratio < 0:
        global _WARNED_ROIALIGN_ADAPTIVE
        if not _WARNED_ROIALIGN_ADAPTIVE:
            import logging
            logging.warning(
                "ROIAlign sample_ratio=-1 (adaptive) needs dynamic "
                "shapes; using a static 2x2 sample grid per bin")
            _WARNED_ROIALIGN_ADAPTIVE = True
        sample_ratio = 2
    sr = builtins.max(int(sample_ratio), 1)

    def f(x, r):
        def one(roi):
            b = roi[0].astype(jnp.int32)
            x1 = roi[1] * spatial_scale
            y1 = roi[2] * spatial_scale
            x2 = roi[3] * spatial_scale
            y2 = roi[4] * spatial_scale
            rw = jnp.maximum(x2 - x1, 1.0)
            rh = jnp.maximum(y2 - y1, 1.0)
            fm = x[b]                                     # (C, H, W)
            h, w = fm.shape[1], fm.shape[2]
            bin_h, bin_w = rh / ph, rw / pw
            # sr x sr sample points per output bin, averaged
            iy = jnp.arange(ph * sr, dtype=jnp.float32)
            ix = jnp.arange(pw * sr, dtype=jnp.float32)
            sy = y1 + (iy + 0.5) * bin_h / sr             # (ph*sr,)
            sx = x1 + (ix + 0.5) * bin_w / sr             # (pw*sr,)
            gy = sy * 2.0 / jnp.maximum(h - 1, 1) - 1.0
            gx = sx * 2.0 / jnp.maximum(w - 1, 1) - 1.0
            gyy = jnp.broadcast_to(gy[:, None], (ph * sr, pw * sr))
            gxx = jnp.broadcast_to(gx[None, :], (ph * sr, pw * sr))
            sampled = _bilinear_sample(fm, gxx, gyy)      # (C, ph*sr, pw*sr)
            c = sampled.shape[0]
            pooled = sampled.reshape(c, ph, sr, pw, sr).mean(axis=(2, 4))
            if position_sensitive:
                # PS-ROIAlign (R-FCN): bin (i, j) pools its OWN channel
                # group — C = c_out * ph * pw
                c_out = c // (ph * pw)
                g = pooled.reshape(c_out, ph, pw, ph, pw)
                ii = jnp.arange(ph)[:, None]
                jj = jnp.arange(pw)[None, :]
                return g[:, ii, jj, ii, jj]               # (c_out, ph, pw)
            return pooled

        return jax.vmap(one)(r)

    return invoke("ROIAlign", f, [data, rois])


# ---- SSD MultiBox triad (parity: src/operator/contrib/multibox_prior.cc,
#      multibox_target.cc, multibox_detection.cc — the GluonCV-era SSD ops)


@_export
def MultiBoxPrior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                  steps=(-1.0, -1.0), offsets=(0.5, 0.5), **kw):
    """Anchor boxes per feature-map pixel → (1, H*W*A, 4) corners in
    [0, 1], A = len(sizes) + len(ratios) - 1 (all sizes at ratio[0], then
    size[0] at the remaining ratios — upstream's enumeration)."""
    data = _as_nd(data)
    sizes = tuple(float(s) for s in sizes)
    ratios = tuple(float(r) for r in ratios)

    def f(x):
        h, w = x.shape[2], x.shape[3]
        step_y = steps[0] if steps[0] > 0 else 1.0 / h
        step_x = steps[1] if steps[1] > 0 else 1.0 / w
        cy = (jnp.arange(h, dtype=jnp.float32) + offsets[0]) * step_y
        cx = (jnp.arange(w, dtype=jnp.float32) + offsets[1]) * step_x
        wh = []
        for s in sizes:
            r = ratios[0]
            wh.append((s * math.sqrt(r), s / math.sqrt(r)))
        for r in ratios[1:]:
            s = sizes[0]
            wh.append((s * math.sqrt(r), s / math.sqrt(r)))
        wh_j = jnp.asarray(wh, jnp.float32)              # (A, 2)
        cyy, cxx = jnp.meshgrid(cy, cx, indexing="ij")   # (H, W)
        centers = jnp.stack([cxx, cyy], axis=-1).reshape(-1, 1, 2)
        half = wh_j[None, :, :] / 2.0                    # (1, A, 2)
        mins = centers - half                            # (HW, A, 2)
        maxs = centers + half
        out = jnp.concatenate([mins, maxs], axis=-1).reshape(1, -1, 4)
        if clip:
            out = jnp.clip(out, 0.0, 1.0)
        return out

    return invoke("MultiBoxPrior", f, [data])


_WARNED_ROIALIGN_ADAPTIVE = False


def _corner_to_center(b):
    w = b[..., 2] - b[..., 0]
    h = b[..., 3] - b[..., 1]
    return (b[..., 0] + w / 2, b[..., 1] + h / 2, w, h)


def _pairwise_iou(a, b):
    """IoU matrix of corner-format boxes a (..., N, 4) x b (..., M, 4)."""
    ax1, ay1, ax2, ay2 = (a[..., :, None, i] for i in range(4))
    bx1, by1, bx2, by2 = (b[..., None, :, i] for i in range(4))
    iw = jnp.maximum(jnp.minimum(ax2, bx2) - jnp.maximum(ax1, bx1), 0)
    ih = jnp.maximum(jnp.minimum(ay2, by2) - jnp.maximum(ay1, by1), 0)
    inter = iw * ih
    area_a = jnp.maximum(ax2 - ax1, 0) * jnp.maximum(ay2 - ay1, 0)
    area_b = jnp.maximum(bx2 - bx1, 0) * jnp.maximum(by2 - by1, 0)
    return inter / jnp.maximum(area_a + area_b - inter, 1e-12)


@_export
def MultiBoxTarget(anchor, label, cls_pred, overlap_threshold=0.5,
                   ignore_label=-1.0, negative_mining_ratio=-1.0,
                   negative_mining_thresh=0.5,
                   variances=(0.1, 0.1, 0.2, 0.2), **kw):
    """SSD training targets: match anchors to GT boxes and encode offsets
    (parity: multibox_target.cc).  label is (B, M, 5) [cls, x1, y1, x2,
    y2] with -1 padding rows.  Returns (loc_target (B, A*4), loc_mask
    (B, A*4), cls_target (B, A)) — cls_target 0 = background, k+1 = GT
    class k."""
    anchor, label, cls_pred = (_as_nd(anchor), _as_nd(label),
                               _as_nd(cls_pred))
    nds = [anchor, label, cls_pred]
    v = tuple(float(x) for x in variances)

    def f(anc, lab, cp):
        a = anc.reshape(-1, 4)                           # (A, 4)
        na = a.shape[0]

        def one(rows, cpb):
            m_gt = rows.shape[0]
            valid = rows[:, 0] >= 0                      # (M,)
            gt = rows[:, 1:5]                            # (M, 4)
            iou = _pairwise_iou(a, gt)
            iou = jnp.where(valid[None, :], iou, -1.0)   # (A, M)

            best_gt = jnp.argmax(iou, axis=1)            # per anchor
            best_iou = jnp.max(iou, axis=1)
            # force-match: each VALID GT claims its best anchor; padding
            # rows scatter into a spill slot so they cannot clobber a real
            # GT's forced match (duplicate-index .at[].set is unordered)
            best_anchor = jnp.argmax(iou, axis=0)        # (M,)
            scatter_to = jnp.where(valid, best_anchor, na)
            forced = jnp.zeros((na + 1,), bool).at[
                scatter_to].set(True)[:na]
            forced_gt = jnp.zeros((na + 1,), jnp.int32).at[
                scatter_to].set(jnp.arange(m_gt, dtype=jnp.int32))[:na]
            matched = forced | (best_iou >= overlap_threshold)
            gt_idx = jnp.where(forced, forced_gt, best_gt)

            cls_t = jnp.where(
                matched, rows[gt_idx, 0].astype(jnp.float32) + 1.0, 0.0)
            if negative_mining_ratio > 0:
                # hard negative mining (multibox_target.cc): keep only the
                # top ratio*n_pos hardest negatives as background; the
                # rest are ignore_label and drop out of the cls loss
                neg_cand = (~matched) & \
                    (best_iou < negative_mining_thresh)
                hardness = jnp.max(cpb[1:, :], axis=0)   # max fg score
                hardness = jnp.where(neg_cand, hardness, -jnp.inf)
                n_pos = jnp.sum(matched.astype(jnp.int32))
                k = jnp.minimum(
                    (negative_mining_ratio * n_pos).astype(jnp.int32),
                    jnp.sum(neg_cand.astype(jnp.int32)))
                order = jnp.argsort(-hardness)
                rank = jnp.zeros((na,), jnp.int32).at[order].set(
                    jnp.arange(na, dtype=jnp.int32))
                mined = neg_cand & (rank < k)
                cls_t = jnp.where(matched, cls_t,
                                  jnp.where(mined, 0.0,
                                            float(ignore_label)))
            acx, acy, aw, ah = _corner_to_center(a)
            m = gt[gt_idx]
            gcx, gcy, gw, gh = _corner_to_center(m)
            lt = jnp.stack([
                (gcx - acx) / jnp.maximum(aw, 1e-12) / v[0],
                (gcy - acy) / jnp.maximum(ah, 1e-12) / v[1],
                jnp.log(jnp.maximum(gw, 1e-12) /
                        jnp.maximum(aw, 1e-12)) / v[2],
                jnp.log(jnp.maximum(gh, 1e-12) /
                        jnp.maximum(ah, 1e-12)) / v[3]], axis=1)
            mask = matched.astype(jnp.float32)[:, None]
            return (lt * mask).reshape(-1), \
                jnp.broadcast_to(mask, (na, 4)).reshape(-1), cls_t

        lt, lm, ct = jax.vmap(one)(lab, cp)
        return lt, lm, ct

    return invoke("MultiBoxTarget", f, nds, nout=3, differentiable=False)


@_export
def MultiBoxDetection(cls_prob, loc_pred, anchor, clip=True,
                      threshold=0.01, nms_threshold=0.5,
                      force_suppress=False,
                      variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1, **kw):
    """Decode SSD predictions and suppress duplicates (parity:
    multibox_detection.cc).  cls_prob (B, C+1, A) with class 0 =
    background; returns (B, A, 6) rows [cls_id, score, x1, y1, x2, y2],
    suppressed rows -1."""
    cls_prob, loc_pred, anchor = (_as_nd(cls_prob), _as_nd(loc_pred),
                                  _as_nd(anchor))
    v = tuple(float(x) for x in variances)

    def f(cp, lp, anc):
        a = anc.reshape(-1, 4)
        acx, acy, aw, ah = _corner_to_center(a)

        def one(cpb, lpb):
            loc = lpb.reshape(-1, 4)
            cx = loc[:, 0] * v[0] * aw + acx
            cy = loc[:, 1] * v[1] * ah + acy
            w = jnp.exp(loc[:, 2] * v[2]) * aw
            h = jnp.exp(loc[:, 3] * v[3]) * ah
            boxes = jnp.stack([cx - w / 2, cy - h / 2,
                               cx + w / 2, cy + h / 2], axis=1)
            if clip:
                boxes = jnp.clip(boxes, 0.0, 1.0)
            scores = cpb[1:, :]                          # (C, A)
            cls_id = jnp.argmax(scores, axis=0)          # (A,)
            score = jnp.max(scores, axis=0)
            keep = score > threshold
            rows = jnp.concatenate([
                jnp.where(keep, cls_id.astype(jnp.float32), -1.0)[:, None],
                jnp.where(keep, score, -1.0)[:, None], boxes], axis=1)
            return rows

        rows = jax.vmap(one)(cp, lp)
        return rows

    decoded = invoke("MultiBoxDetection_decode", f,
                     [cls_prob, loc_pred, anchor], differentiable=False)
    return box_nms(decoded, overlap_thresh=nms_threshold,
                   valid_thresh=threshold, topk=nms_topk,
                   coord_start=2, score_index=1, id_index=0,
                   force_suppress=force_suppress)
