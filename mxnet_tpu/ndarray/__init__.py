"""mxnet_tpu.nd — imperative NDArray API (parity: mx.nd)."""
from .ndarray import (NDArray, array, from_jax, zeros, ones, full, empty,
                      arange, eye, linspace, concatenate)
from .ops import *  # noqa: F401,F403
from . import ops
from .ops import invoke

# convenience: mx.nd.waitall parity
import jax as _jax


def waitall():
    """Block until all async work completes (parity: mx.nd.waitall)."""
    (_jax.effects_barrier if hasattr(_jax, "effects_barrier") else
     (lambda: None))()


def save(fname, data):
    from ..utils.serialization import save as _save
    _save(fname, data)


def load(fname):
    from ..utils.serialization import load as _load
    return _load(fname)


def Custom(*data, op_type, **kwargs):
    """Invoke a registered Python custom op (parity: mx.nd.Custom)."""
    from ..operator import Custom as _custom
    return _custom(*data, op_type=op_type, **kwargs)


def __getattr__(name):
    # mx.nd.contrib / mx.nd.sparse resolve lazily (import cost + cycles)
    if name == "contrib":
        from .. import contrib
        globals()["contrib"] = contrib
        return contrib
    if name == "sparse":
        import importlib
        mod = importlib.import_module(".sparse", __name__)
        globals()["sparse"] = mod
        return mod
    if name == "cast_storage":
        from .sparse import cast_storage
        globals()["cast_storage"] = cast_storage
        return cast_storage
    raise AttributeError(f"module 'mxnet_tpu.ndarray' has no attribute "
                         f"{name!r}")
