"""Sparse NDArray types (parity: python/mxnet/ndarray/sparse.py + the CSR/
RowSparse storage kernels spread through src/operator/tensor — SURVEY.md
§2.3 "Sparse ops").

TPU-first stance: the MXU has no sparse formats, so sparse here is a
*storage* optimization with explicit dense boundaries — exactly MXNet's
semantics, where most ops on sparse inputs fall back to dense with a storage
warning.  Compact components (data/indices/indptr) live as device arrays;
``dot(csr, dense)`` uses gather/segment-sum (XLA-native), RowSparse drives
the optimizers' lazy row-wise updates, and anything else densifies.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as onp

from .. import base as _base
from ..context import current_context
from .ndarray import NDArray, array as nd_array, from_jax

__all__ = ["CSRNDArray", "RowSparseNDArray", "csr_matrix",
           "row_sparse_array", "cast_storage", "zeros", "empty", "dot",
           "BaseSparseNDArray", "sparse_add", "retain"]


class BaseSparseNDArray(NDArray):
    """Common base: dense value materialized lazily from components.

    The dense payload is built on first ``.jax`` access and cached — a
    row-sparse gradient that only ever meets the lazy-update optimizer
    path never allocates its (vocab, dim) dense form.
    """

    __slots__ = ()

    def __init__(self, ctx=None):
        super().__init__(None, ctx=ctx)

    def _materialize(self):
        raise NotImplementedError

    @property
    def jax(self):
        if self._data is None:
            self._data = self._materialize()
        return self._data

    # metadata must come from the components — reading .jax here would
    # silently materialize (and cache) the full dense buffer on an
    # incidental shape/dtype inspection
    @property
    def shape(self):
        return tuple(self._sp_shape)

    @property
    def dtype(self):
        return onp.dtype(self._sp_data.dtype)

    @property
    def stype(self):
        raise NotImplementedError

    def tostype(self, stype: str):
        if stype == "default":
            return NDArray(self.jax, ctx=self.context)
        if stype == self.stype:
            return self
        return cast_storage(self, stype)

    def todense(self) -> NDArray:
        return NDArray(self.jax, ctx=self.context)

    def asscipy(self):
        raise _base.MXNetError("scipy interop not available")


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix (parity: mx.nd.sparse.CSRNDArray)."""

    __slots__ = ("_sp_data", "_sp_indices", "_sp_indptr", "_sp_shape")

    def __init__(self, data, indices, indptr, shape, ctx=None):
        self._sp_data = jnp.asarray(data)
        self._sp_indices = jnp.asarray(indices, jnp.int32)
        self._sp_indptr = jnp.asarray(indptr, jnp.int32)
        self._sp_shape = tuple(shape)
        super().__init__(ctx=ctx)

    def _materialize(self):
        return _csr_to_dense(self._sp_data, self._sp_indices,
                             self._sp_indptr, self._sp_shape)

    @property
    def stype(self):
        return "csr"

    @property
    def data(self) -> NDArray:
        return from_jax(self._sp_data)

    @property
    def indices(self) -> NDArray:
        return from_jax(self._sp_indices)

    @property
    def indptr(self) -> NDArray:
        return from_jax(self._sp_indptr)

    def __repr__(self):
        return (f"<CSRNDArray {self._sp_shape} "
                f"nnz={int(self._sp_data.shape[0])}>")


class RowSparseNDArray(BaseSparseNDArray):
    """Row-sparse tensor: a subset of rows is stored (parity:
    mx.nd.sparse.RowSparseNDArray; the storage type of sparse gradients)."""

    __slots__ = ("_sp_data", "_sp_indices", "_sp_shape")

    def __init__(self, data, indices, shape, ctx=None):
        self._sp_data = jnp.asarray(data)
        self._sp_indices = jnp.asarray(indices, jnp.int32)
        self._sp_shape = tuple(shape)
        super().__init__(ctx=ctx)

    def _materialize(self):
        return jnp.zeros(self._sp_shape, self._sp_data.dtype).at[
            self._sp_indices].set(self._sp_data)

    def _set_components(self, data, indices):
        """Rebind the compact payload in place (used by row_sparse_pull and
        in-place gradient writes); invalidates any cached dense
        materialization."""
        self._sp_data = jnp.asarray(data)
        self._sp_indices = jnp.asarray(indices, jnp.int32)
        self._data = None

    def _set_dense(self, full):
        """Rebind from a dense value in place (every row present) — the
        dense-gradient-into-row-sparse-buffer fallback, keeping held
        handles and the declared stype valid."""
        self._sp_shape = tuple(full.shape)
        self._sp_indices = jnp.arange(full.shape[0], dtype=jnp.int32)
        self._sp_data = full
        self._data = full

    @property
    def stype(self):
        return "row_sparse"

    @property
    def data(self) -> NDArray:
        return from_jax(self._sp_data)

    @property
    def indices(self) -> NDArray:
        return from_jax(self._sp_indices)

    def __repr__(self):
        return (f"<RowSparseNDArray {self._sp_shape} "
                f"rows={int(self._sp_indices.shape[0])}>")

    @classmethod
    def from_components(cls, data, indices, shape, ctx=None):
        """Build directly from device arrays without a host round-trip
        (the gradient-path constructor — stays compact until ``.jax``)."""
        obj = cls.__new__(cls)
        obj._sp_data = data if _is_jax(data) else jnp.asarray(data)
        obj._sp_indices = (indices if _is_jax(indices)
                           else jnp.asarray(indices, jnp.int32))
        obj._sp_shape = tuple(shape)
        NDArray.__init__(obj, None, ctx=ctx)
        return obj


def _is_jax(x):
    return isinstance(x, (jax.Array, jax.core.Tracer))


class _RowSparseCot:
    """Compact row-sparse cotangent flowing through the autograd tape
    (parity: the RowSparse gradient stype of Embedding(sparse_grad=True),
    SURVEY §2.3 `src/operator/tensor/indexing_op.*`).

    `data` is (n_rows, ...) jax, `indices` (n_rows,) int32 with UNIQUE
    entries, `shape` the full dense shape.  Supports `+` against both
    other cots (compact merge) and dense arrays (densify) because the
    tape accumulates with plain addition.
    """

    __slots__ = ("data", "indices", "shape")

    def __init__(self, data, indices, shape):
        self.data = data
        self.indices = indices
        self.shape = tuple(shape)

    def to_dense(self):
        return jnp.zeros(self.shape, self.data.dtype).at[
            self.indices].add(self.data)

    def __add__(self, other):
        if isinstance(other, _RowSparseCot):
            idx = onp.concatenate([onp.asarray(self.indices),
                                   onp.asarray(other.indices)])
            uniq, inv = onp.unique(idx, return_inverse=True)
            data = jax.ops.segment_sum(
                jnp.concatenate([self.data, other.data], axis=0),
                jnp.asarray(inv, jnp.int32), num_segments=len(uniq))
            return _RowSparseCot(data, jnp.asarray(uniq, jnp.int32),
                                 self.shape)
        if other is None or (isinstance(other, int) and other == 0):
            return self
        return self.to_dense() + other

    __radd__ = __add__


def _csr_row_ids(indptr, nnz):
    """Row id per nnz entry from the CSR indptr (searchsorted over the
    nnz positions)."""
    return jnp.searchsorted(indptr[1:], jnp.arange(nnz), side="right")


def _csr_to_dense(data, indices, indptr, shape):
    nnz = data.shape[0]
    if nnz == 0:
        return jnp.zeros(shape, data.dtype)
    rows = _csr_row_ids(indptr, nnz)
    dense = jnp.zeros(shape, data.dtype)
    return dense.at[rows, indices].set(data)


# ----------------------------------------------------------------- factory

def csr_matrix(arg1, shape=None, ctx=None, dtype=None) -> CSRNDArray:
    """Create a CSRNDArray from (data, indices, indptr) or a dense array."""
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        data = data.asnumpy() if isinstance(data, NDArray) else \
            onp.asarray(data)
        if dtype is not None:
            data = data.astype(_base.canonical_dtype(dtype))
        indices = indices.asnumpy() if isinstance(indices, NDArray) else \
            onp.asarray(indices)
        indptr = indptr.asnumpy() if isinstance(indptr, NDArray) else \
            onp.asarray(indptr)
        return CSRNDArray(data, indices, indptr, shape, ctx=ctx)
    dense = arg1.asnumpy() if isinstance(arg1, NDArray) else \
        onp.asarray(arg1, dtype=onp.float32)
    return _dense_to_csr(dense, ctx)


def _dense_to_csr(dense: onp.ndarray, ctx=None) -> CSRNDArray:
    mask = dense != 0
    indptr = onp.concatenate([[0], mask.sum(axis=1).cumsum()]).astype("int64")
    indices = onp.nonzero(mask)[1]
    data = dense[mask]
    return CSRNDArray(data, indices, indptr, dense.shape, ctx=ctx)


def row_sparse_array(arg1, shape=None, ctx=None,
                     dtype=None) -> RowSparseNDArray:
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        data = data.asnumpy() if isinstance(data, NDArray) else \
            onp.asarray(data)
        if dtype is not None:
            data = data.astype(_base.canonical_dtype(dtype))
        indices = indices.asnumpy() if isinstance(indices, NDArray) else \
            onp.asarray(indices)
        return RowSparseNDArray(data, indices, shape, ctx=ctx)
    dense = arg1.asnumpy() if isinstance(arg1, NDArray) else \
        onp.asarray(arg1, dtype=onp.float32)
    nz_rows = onp.nonzero((dense != 0).any(axis=tuple(
        range(1, dense.ndim))))[0]
    return RowSparseNDArray(dense[nz_rows], nz_rows, dense.shape, ctx=ctx)


def cast_storage(arr: NDArray, stype: str):
    """Convert between storage types (parity: mx.nd.cast_storage)."""
    if stype == "default":
        return NDArray(arr.jax, ctx=arr.context)
    dense = arr.asnumpy()
    if stype == "csr":
        if dense.ndim != 2:
            raise _base.MXNetError("csr storage requires 2-D")
        return _dense_to_csr(dense, arr.context)
    if stype == "row_sparse":
        nz = onp.nonzero((dense != 0).any(axis=tuple(
            range(1, dense.ndim))))[0]
        return RowSparseNDArray(dense[nz], nz, dense.shape, ctx=arr.context)
    raise _base.MXNetError(f"unknown stype {stype!r}")


def zeros(stype, shape, ctx=None, dtype="float32"):
    dt = _base.canonical_dtype(dtype)
    if stype == "csr":
        return CSRNDArray(onp.zeros((0,), dt), onp.zeros((0,), "int64"),
                          onp.zeros((shape[0] + 1,), "int64"), shape, ctx)
    if stype == "row_sparse":
        return RowSparseNDArray(onp.zeros((0,) + tuple(shape[1:]), dt),
                                onp.zeros((0,), "int64"), shape, ctx)
    from .ndarray import zeros as dzeros
    return dzeros(shape, ctx=ctx, dtype=dtype)


def empty(stype, shape, ctx=None, dtype="float32"):
    return zeros(stype, shape, ctx, dtype)


# --------------------------------------------------------------- operators

def dot(lhs, rhs, transpose_a=False, transpose_b=False) -> NDArray:
    """Sparse-aware dot.  csr·dense (and csrᵀ·dense — the gradient/
    embedding-bag direction) use gather+segment-sum (XLA-native) WITHOUT
    densifying the csr side, dispatched through ``invoke`` so the dense
    operand gets a normal autograd pullback (the classic MXNet pattern:
    csr features are data, the dense rhs is the parameter).  Everything
    else goes through the dense path."""
    if isinstance(lhs, CSRNDArray) and isinstance(rhs, NDArray) and \
            not isinstance(rhs, BaseSparseNDArray):
        from . import ops as _ops
        data, indices, indptr = (lhs._sp_data, lhs._sp_indices,
                                 lhs._sp_indptr)
        nnz = data.shape[0]
        n_rows, n_cols = lhs._sp_shape
        # nnz == 0 flows through the same invoke path (empty gather +
        # segment_sum = zeros) so the output is ALWAYS on the tape — an
        # all-empty batch must not silently skip the grad edge
        rows = _csr_row_ids(indptr, nnz)

        def f(r):
            if transpose_b:
                r = r.T
            if transpose_a:
                # outᵀ[j] = Σ_{k: col(k)=j} data[k] * r[row(k)]
                gathered = r[rows] * data[:, None]
                return jax.ops.segment_sum(gathered, indices,
                                           num_segments=n_cols)
            gathered = r[indices] * data[:, None]   # (nnz, N)
            return jax.ops.segment_sum(gathered, rows,
                                       num_segments=n_rows)

        return _ops.invoke("sparse_dot", f, [rhs])
    from . import ops as _ops
    l = lhs.todense() if isinstance(lhs, BaseSparseNDArray) else lhs
    rr = rhs.todense() if isinstance(rhs, BaseSparseNDArray) else rhs
    return _ops.dot(l, rr, transpose_a=transpose_a, transpose_b=transpose_b)


def sparse_add(lhs, rhs):
    """Elementwise add; RowSparse + RowSparse stays COMPACT (merged
    unique rows), anything else goes dense."""
    if isinstance(lhs, RowSparseNDArray) and \
            isinstance(rhs, RowSparseNDArray) and \
            lhs._sp_shape == rhs._sp_shape:
        cot = _RowSparseCot(lhs._sp_data, lhs._sp_indices, lhs._sp_shape) \
            + _RowSparseCot(rhs._sp_data, rhs._sp_indices, rhs._sp_shape)
        return RowSparseNDArray.from_components(
            cot.data, cot.indices, cot.shape, ctx=lhs.context)
    from . import ops as _ops
    l = lhs.todense() if isinstance(lhs, BaseSparseNDArray) else lhs
    r = rhs.todense() if isinstance(rhs, BaseSparseNDArray) else rhs
    return _ops.add(l, r)


def retain(data: RowSparseNDArray, indices) -> RowSparseNDArray:
    """Keep only the requested rows (parity: mx.nd.sparse.retain)."""
    idx = indices.asnumpy().astype("int64") if isinstance(indices, NDArray) \
        else onp.asarray(indices, "int64")
    have = data._sp_indices
    keep_mask = jnp.isin(have, jnp.asarray(idx))
    keep = onp.nonzero(onp.asarray(keep_mask))[0]
    return RowSparseNDArray(onp.asarray(data._sp_data)[keep],
                            onp.asarray(have)[keep], data._sp_shape,
                            ctx=data.context)
