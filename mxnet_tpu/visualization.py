"""``mx.viz`` — network visualization (parity: python/mxnet/visualization.py).

print_summary walks a Symbol and prints the layer table; plot_network
emits a graphviz Digraph when the (optional) graphviz package exists and
raises a clear error otherwise (the package is not baked into this image).
"""
from __future__ import annotations

from . import base as _base

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol, shape=None, line_length=120):
    """Print nodes of a Symbol DAG with op/name/inputs columns
    (parity: mx.viz.print_summary)."""
    nodes = []
    seen = set()

    def walk(s):
        if id(s) in seen:
            return
        seen.add(id(s))
        for i in s._inputs:
            walk(i)
        nodes.append(s)

    roots = symbol._inputs if symbol._op == "group" else [symbol]
    for r in roots:
        walk(r)
    hdr = f"{'Layer (type)':<40}{'Op':<24}{'Inputs':<40}"
    print("=" * line_length)
    print(hdr)
    print("=" * line_length)
    for n in nodes:
        ins = ", ".join(i._name for i in n._inputs)
        print(f"{n._name:<40}{n._op:<24}{ins:<40}")
    print("=" * line_length)
    print(f"Total nodes: {len(nodes)}")
    return nodes


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None):
    """Graphviz Digraph of a Symbol (parity: mx.viz.plot_network).
    Requires the optional ``graphviz`` package."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise _base.MXNetError(
            "plot_network needs the optional 'graphviz' package (not "
            "installed in this image); use mx.viz.print_summary for a "
            "text rendering") from e
    dot = Digraph(name=title, format=save_format)
    seen = set()

    def walk(s):
        if id(s) in seen:
            return
        seen.add(id(s))
        shape_attr = ("oval" if s._op == "null" else "box")
        dot.node(str(id(s)), f"{s._name}\n{s._op}", shape=shape_attr)
        for i in s._inputs:
            walk(i)
            dot.edge(str(id(i)), str(id(s)))

    roots = symbol._inputs if symbol._op == "group" else [symbol]
    for r in roots:
        walk(r)
    return dot
