"""``mx.operator`` — Python custom operators.

Parity target: python/mxnet/operator.py + src/operator/custom/custom.cc
(SURVEY.md §2.3): ``CustomOp``/``CustomOpProp`` subclasses registered by
name, invoked via ``mx.nd.Custom(..., op_type=name)``.

TPU-first note: custom ops written against this API run as host callbacks
(eager) — same as MXNet, where custom ops ran on a special engine path that
synchronized with Python.  Gradients integrate with the autograd tape via
the same mechanism as autograd.Function.  For jit-compatible custom kernels
use ``mxnet_tpu.ops`` (pure-JAX/Pallas) instead.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as onp

from . import base as _base
from .autograd.tape import OpNode, OutRef, node_of
from .ndarray import NDArray, array as nd_array

__all__ = ["CustomOp", "CustomOpProp", "register", "get_entry", "Custom"]

_custom_registry = _base.registry("custom_op")


class CustomOp:
    """Base for custom operator implementations."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst: NDArray, req: str, src):
        if req in ("null", None):
            return
        src_val = src.jax if isinstance(src, NDArray) else \
            nd_array(src).jax
        if req in ("write", "inplace"):
            dst._rebind(src_val)
        elif req == "add":
            dst._rebind(dst.jax + src_val)
        else:
            raise _base.MXNetError(f"unknown req {req!r}")


class CustomOpProp:
    """Describes a custom op: shapes, dtypes, arg names."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self) -> List[str]:
        return ["data"]

    def list_outputs(self) -> List[str]:
        return ["output"]

    def list_auxiliary_states(self) -> List[str]:
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), []

    def create_operator(self, ctx, in_shapes, in_dtypes) -> CustomOp:
        raise NotImplementedError

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps


def register(reg_name: str):
    """Class decorator registering a CustomOpProp by name."""
    def do_register(prop_cls):
        _custom_registry.register(reg_name)(prop_cls)
        return prop_cls
    return do_register


def get_entry(name: str):
    return _custom_registry.get(name)


def Custom(*data, op_type: str, **kwargs) -> NDArray:
    """Invoke a registered custom op on NDArray inputs
    (parity: mx.nd.Custom)."""
    prop_cls = _custom_registry.get(op_type)
    import inspect
    sig = inspect.signature(prop_cls.__init__)
    accepted = {k: v for k, v in kwargs.items()
                if k in sig.parameters}
    prop = prop_cls(**accepted)
    in_shapes = [tuple(d.shape) for d in data]
    in_shapes_out, out_shapes, aux_shapes = prop.infer_shape(in_shapes)
    in_types = [d.dtype for d in data]
    _, out_types, _ = prop.infer_type(in_types)
    op = prop.create_operator(None, in_shapes_out, in_types)

    from .ndarray import zeros as nd_zeros
    out_data = [nd_zeros(s, dtype=str(onp.dtype(t)))
                for s, t in zip(out_shapes, out_types)]
    aux = []
    is_train = _base.is_training()
    req = ["write"] * len(out_data)
    op.forward(is_train, req, list(data), out_data, aux)

    if _base.is_recording():
        in_nodes = [node_of(d) for d in data]
        if any(n is not None for n in in_nodes):
            data_snapshot = list(data)
            outs_snapshot = list(out_data)

            def vjp_fn(cots):
                cots_t = (cots,) if len(out_data) == 1 else tuple(cots)
                in_grad = [nd_zeros(tuple(d.shape), dtype=str(d.dtype))
                           for d in data_snapshot]
                with _base.training_mode(_base.is_training()):
                    rec = _base.set_recording(False)
                    try:
                        op.backward(["write"] * len(in_grad),
                                    [NDArray(c) for c in cots_t],
                                    data_snapshot, outs_snapshot, in_grad,
                                    aux)
                    finally:
                        _base.set_recording(rec)
                return tuple(g.jax for g in in_grad)

            import jax
            node = OpNode(vjp_fn, in_nodes, len(out_data), name=op_type,
                          out_avals=[jax.ShapeDtypeStruct(o.shape,
                                                          o.jax.dtype)
                                     for o in out_data])
            for i, o in enumerate(out_data):
                o._node = OutRef(node, i)

    return out_data[0] if len(out_data) == 1 else out_data
