"""``mx.engine`` compatibility (parity: python/mxnet/engine.py).

The threaded dependency engine is absorbed by XLA's async dispatch
(SURVEY.md §7.1): ``bulk()`` — upstream's batching of engine ops to cut
per-op overhead — is a no-op context manager because jit tracing already
bulks entire programs, and ``set_bulk_size`` returns the previous value
without effect.  Kept so scripts using these knobs run unchanged.
"""
from __future__ import annotations

import contextlib

__all__ = ["bulk", "set_bulk_size"]

_bulk_size = 15


def set_bulk_size(size: int) -> int:
    global _bulk_size
    prev, _bulk_size = _bulk_size, int(size)
    return prev


@contextlib.contextmanager
def bulk(size: int):
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)
