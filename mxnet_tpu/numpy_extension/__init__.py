"""``mx.npx`` — numpy-extension namespace (parity: python/mxnet/numpy_extension
+ ``mx.npx`` operator surface from src/operator/numpy/ non-numpy ops).

These are the deep-learning ops that fall outside the NumPy standard
(activation/norm/conv/pooling/embedding/...).  They delegate to the
``mx.nd`` implementations, which are pure JAX functions — so npx code
hybridizes into the same single XLA computation.
"""
from __future__ import annotations

import threading as _threading

from .. import base as _base
from ..ndarray import ops as _nd
from ..ndarray.ndarray import NDArray

__all__ = ["set_np", "reset_np", "is_np_array", "is_np_shape",
           "is_np_default_dtype", "use_np", "np_shape", "np_array"]

_np_state = _threading.local()


def set_np(shape=True, array=True, dtype=False):
    _np_state.shape = shape
    _np_state.array = array
    _np_state.dtype = dtype


def reset_np():
    set_np(False, False, False)


def is_np_array():
    return getattr(_np_state, "array", False)


def is_np_shape():
    return getattr(_np_state, "shape", False)


def is_np_default_dtype():
    return getattr(_np_state, "dtype", False)


class _NpScope:
    def __init__(self, shape=True, array=True, dtype=False):
        self._new = (shape, array, dtype)

    def __enter__(self):
        self._old = (is_np_shape(), is_np_array(), is_np_default_dtype())
        set_np(*self._new)
        return self

    def __exit__(self, *a):
        set_np(*self._old)

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with _NpScope(*self._new):
                return fn(*args, **kwargs)
        return wrapped


def use_np(fn=None):
    scope = _NpScope(True, True, False)
    return scope(fn) if fn is not None else scope


def np_shape(active=True):
    return _NpScope(active, is_np_array(), is_np_default_dtype())


def np_array(active=True):
    return _NpScope(is_np_shape(), active, is_np_default_dtype())


# ----------------------------------------------------------- op delegation

_DELEGATED = [
    # activations / nn
    "relu", "sigmoid", "softmax", "log_softmax", "softplus", "softsign",
    "erf", "erfinv", "gamma", "gammaln",
    # layers
    "activation", "batch_norm", "layer_norm", "group_norm", "instance_norm",
    "convolution", "deconvolution", "fully_connected", "pooling", "dropout",
    "embedding", "rnn", "leaky_relu", "l2_normalization",
    # indexing / shape
    "one_hot", "pick", "topk", "gather_nd", "scatter_nd", "reshape_like",
    "broadcast_like", "arange_like", "shape_array", "slice", "slice_axis",
    "slice_like", "sequence_mask", "batch_dot",
    # misc
    "smooth_l1", "multibox_detection", "multibox_prior",
    "multibox_target", "sample_multinomial", "batch_flatten",
    "roi_pooling",
]

_ALIAS_TO_ND = {
    "activation": "Activation",
    "batch_norm": "BatchNorm",
    "layer_norm": "LayerNorm",
    "group_norm": "GroupNorm",
    "instance_norm": "InstanceNorm",
    "convolution": "Convolution",
    "deconvolution": "Deconvolution",
    "fully_connected": "FullyConnected",
    "pooling": "Pooling",
    "dropout": "Dropout",
    "embedding": "Embedding",
    "rnn": "RNN",
    "leaky_relu": "LeakyReLU",
    "l2_normalization": "L2Normalization",
    "sequence_mask": "SequenceMask",
    "multibox_detection": "MultiBoxDetection",
    "multibox_prior": "MultiBoxPrior",
    "multibox_target": "MultiBoxTarget",
    "batch_flatten": "Flatten",
    "roi_pooling": "ROIPooling",
}

for _name in _DELEGATED:
    _target = _ALIAS_TO_ND.get(_name, _name)
    _fn = getattr(_nd, _target, None)
    if _fn is not None:
        globals()[_name] = _fn
        __all__.append(_name)


def save(file, arr):
    """Save dict/list of np arrays (same container as mx.nd.save)."""
    _nd.save(file, arr)


def load(file):
    return _nd.load(file)


def waitall():
    _nd.waitall()


def seed(s):
    from .. import random as _random
    _random.seed(int(s))


from ..context import cpu, gpu, num_gpus  # noqa: E402,F401
from ..context import current_context as current_device  # noqa: E402,F401


def masked_softmax(data, mask=None, axis=-1, temperature=1.0):
    """Parity: npx.masked_softmax — softmax over positions where mask is
    True; masked positions get probability 0 (all-masked rows get 0)."""
    import jax.numpy as jnp

    nds = [_nd._as_nd(data)]
    has_mask = mask is not None
    if has_mask:
        nds.append(_nd._as_nd(mask))

    def f(x, *m):
        x = x / temperature
        if m:
            x = jnp.where(m[0].astype(bool), x, -1e30)
        e = jnp.exp(x - jnp.max(x, axis=axis, keepdims=True))
        if m:
            e = jnp.where(m[0].astype(bool), e, 0.0)
        s = jnp.sum(e, axis=axis, keepdims=True)
        return jnp.where(s > 0, e / jnp.maximum(s, 1e-30), 0.0)

    return _nd.invoke("masked_softmax", f, nds)


def masked_log_softmax(data, mask=None, axis=-1, temperature=1.0):
    """log-softmax over unmasked positions; masked positions get -inf
    (parity: npx.masked_log_softmax)."""
    import jax.numpy as jnp

    nds = [_nd._as_nd(data)]
    has_mask = mask is not None
    if has_mask:
        nds.append(_nd._as_nd(mask))

    def f(x, *m):
        import jax
        x = x / temperature
        if m:
            x = jnp.where(m[0].astype(bool), x, -1e30)
        out = x - jax.nn.logsumexp(x, axis=axis, keepdims=True)
        if m:
            out = jnp.where(m[0].astype(bool), out, -jnp.inf)
        return out

    return _nd.invoke("masked_log_softmax", f, nds)


def _npx_reshape_shape(in_shape, newshape):
    """Resolve the MXNet 2.x npx.reshape special codes (parity:
    NumpyXReshapeInferShape, src/operator/numpy/np_matrix_op.cc):
    -1 infer, -2 copy input dim, -3 skip a size-1 input dim, -4 copy all
    remaining input dims, -5 fuse two consecutive input dims, -6 split
    an input dim into the following two entries (one may be -1), 0 is a
    literal zero-size dim."""
    out, i, j = [], 0, 0
    ns = list(newshape)
    infer_pos = None
    while j < len(ns):
        s = int(ns[j])
        if s >= 0:
            out.append(s)
            i += 1
        elif s == -1:
            if infer_pos is not None:
                raise _base.MXNetError("npx.reshape: at most one -1")
            infer_pos = len(out)
            out.append(-1)
            i += 1
        elif s == -2:
            out.append(in_shape[i])
            i += 1
        elif s == -3:
            if in_shape[i] != 1:
                raise _base.MXNetError(
                    f"npx.reshape: -3 skips a size-1 dim, input dim {i} "
                    f"has size {in_shape[i]}")
            i += 1
        elif s == -4:
            out.extend(in_shape[i:])
            i = len(in_shape)
        elif s == -5:
            out.append(in_shape[i] * in_shape[i + 1])
            i += 2
        elif s == -6:
            a, b = int(ns[j + 1]), int(ns[j + 2])
            j += 2
            d = in_shape[i]
            i += 1
            if a == -1:
                a = d // b
            elif b == -1:
                b = d // a
            if a * b != d:
                raise _base.MXNetError(
                    f"npx.reshape: cannot split dim of size {d} into "
                    f"({ns[j - 1]}, {ns[j]})")
            out.extend([a, b])
        else:
            raise _base.MXNetError(
                f"npx.reshape: unknown special value {s}")
        j += 1
    if infer_pos is not None:
        total = 1
        for d in in_shape:
            total *= d
        known = 1
        for d in out:
            if d != -1:
                known *= d
        out[infer_pos] = total // max(known, 1)
    return tuple(out)


def reshape(a, newshape, reverse=False, order="C"):
    """MXNet 2.x npx.reshape — NOT the legacy nd.reshape dialect (the
    special-value codes differ; see _npx_reshape_shape)."""
    import jax.numpy as jnp

    a_nd = _nd._as_nd(a)
    in_shape = tuple(a_nd.shape)
    if reverse:
        if any(int(s) == -6 for s in newshape):
            raise _base.MXNetError(
                "npx.reshape: reverse=True with -6 is not supported")
        shape = _npx_reshape_shape(in_shape[::-1],
                                   list(newshape)[::-1])[::-1]
    else:
        shape = _npx_reshape_shape(in_shape, newshape)
    return _nd.invoke("npx_reshape", lambda x: jnp.reshape(x, shape),
                      [a_nd])


def nonzero(a):
    """Indices of nonzero elements as an (N, ndim) int64 array (parity:
    npx.nonzero).  Eager-only: the output shape is data-dependent, so it
    cannot run inside jit/hybridize traces."""
    import jax
    import numpy as onp

    a_nd = _nd._as_nd(a)
    if isinstance(a_nd.jax, jax.core.Tracer):
        raise _base.MXNetError(
            "npx.nonzero has a data-dependent output shape and cannot be "
            "traced (jit/hybridize); call it eagerly")
    idx = onp.nonzero(onp.asarray(a_nd.jax))
    from ..ndarray.ndarray import array as _array
    return _array(onp.stack(idx, axis=1).astype("int64"), dtype="int64")


__all__ += ["save", "load", "waitall", "seed", "cpu", "gpu", "num_gpus",
            "current_device", "masked_softmax", "masked_log_softmax",
            "nonzero", "reshape"]
