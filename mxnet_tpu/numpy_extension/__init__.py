"""``mx.npx`` — numpy-extension namespace (parity: python/mxnet/numpy_extension
+ ``mx.npx`` operator surface from src/operator/numpy/ non-numpy ops).

These are the deep-learning ops that fall outside the NumPy standard
(activation/norm/conv/pooling/embedding/...).  They delegate to the
``mx.nd`` implementations, which are pure JAX functions — so npx code
hybridizes into the same single XLA computation.
"""
from __future__ import annotations

import threading as _threading

from .. import base as _base
from ..ndarray import ops as _nd
from ..ndarray.ndarray import NDArray

__all__ = ["set_np", "reset_np", "is_np_array", "is_np_shape",
           "is_np_default_dtype", "use_np", "np_shape", "np_array"]

_np_state = _threading.local()


def set_np(shape=True, array=True, dtype=False):
    _np_state.shape = shape
    _np_state.array = array
    _np_state.dtype = dtype


def reset_np():
    set_np(False, False, False)


def is_np_array():
    return getattr(_np_state, "array", False)


def is_np_shape():
    return getattr(_np_state, "shape", False)


def is_np_default_dtype():
    return getattr(_np_state, "dtype", False)


class _NpScope:
    def __init__(self, shape=True, array=True, dtype=False):
        self._new = (shape, array, dtype)

    def __enter__(self):
        self._old = (is_np_shape(), is_np_array(), is_np_default_dtype())
        set_np(*self._new)
        return self

    def __exit__(self, *a):
        set_np(*self._old)

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with _NpScope(*self._new):
                return fn(*args, **kwargs)
        return wrapped


def use_np(fn=None):
    scope = _NpScope(True, True, False)
    return scope(fn) if fn is not None else scope


def np_shape(active=True):
    return _NpScope(active, is_np_array(), is_np_default_dtype())


def np_array(active=True):
    return _NpScope(is_np_shape(), active, is_np_default_dtype())


# ----------------------------------------------------------- op delegation

_DELEGATED = [
    # activations / nn
    "relu", "sigmoid", "softmax", "log_softmax", "softplus", "softsign",
    "erf", "erfinv", "gamma", "gammaln",
    # layers
    "activation", "batch_norm", "layer_norm", "group_norm", "instance_norm",
    "convolution", "deconvolution", "fully_connected", "pooling", "dropout",
    "embedding", "rnn", "leaky_relu", "l2_normalization",
    # indexing / shape
    "one_hot", "pick", "topk", "gather_nd", "scatter_nd", "reshape_like",
    "broadcast_like", "arange_like", "shape_array", "slice", "slice_axis",
    "slice_like", "sequence_mask", "batch_dot",
    # misc
    "smooth_l1", "multibox_detection", "sample_multinomial",
]

_ALIAS_TO_ND = {
    "activation": "Activation",
    "batch_norm": "BatchNorm",
    "layer_norm": "LayerNorm",
    "group_norm": "GroupNorm",
    "instance_norm": "InstanceNorm",
    "convolution": "Convolution",
    "deconvolution": "Deconvolution",
    "fully_connected": "FullyConnected",
    "pooling": "Pooling",
    "dropout": "Dropout",
    "embedding": "Embedding",
    "rnn": "RNN",
    "leaky_relu": "LeakyReLU",
    "l2_normalization": "L2Normalization",
    "sequence_mask": "SequenceMask",
}

for _name in _DELEGATED:
    _target = _ALIAS_TO_ND.get(_name, _name)
    _fn = getattr(_nd, _target, None)
    if _fn is not None:
        globals()[_name] = _fn
        __all__.append(_name)


def save(file, arr):
    """Save dict/list of np arrays (same container as mx.nd.save)."""
    _nd.save(file, arr)


def load(file):
    return _nd.load(file)


def waitall():
    _nd.waitall()


def seed(s):
    from .. import random as _random
    _random.seed(int(s))


from ..context import cpu, gpu, num_gpus  # noqa: E402,F401
from ..context import current_context as current_device  # noqa: E402,F401


def masked_softmax(data, mask=None, axis=-1, temperature=1.0):
    """Parity: npx.masked_softmax — softmax over positions where mask is
    True; masked positions get probability 0 (all-masked rows get 0)."""
    import jax.numpy as jnp

    nds = [_nd._as_nd(data)]
    has_mask = mask is not None
    if has_mask:
        nds.append(_nd._as_nd(mask))

    def f(x, *m):
        x = x / temperature
        if m:
            x = jnp.where(m[0].astype(bool), x, -1e30)
        e = jnp.exp(x - jnp.max(x, axis=axis, keepdims=True))
        if m:
            e = jnp.where(m[0].astype(bool), e, 0.0)
        s = jnp.sum(e, axis=axis, keepdims=True)
        return jnp.where(s > 0, e / jnp.maximum(s, 1e-30), 0.0)

    return _nd.invoke("masked_softmax", f, nds)


__all__ += ["save", "load", "waitall", "seed", "cpu", "gpu", "num_gpus",
            "current_device", "masked_softmax"]
