"""``mx.amp`` — automatic mixed precision.

Parity target: python/mxnet/amp (2.x) / contrib/amp (1.x): op allow/deny
lists + ``amp_cast`` insertion + dynamic LossScaler (SURVEY.md §2.6,
src/nnvm/low_precision_pass.cc).

TPU-first realization: instead of monkey-patching generated op namespaces
and inserting cast nodes into an NNVM graph, ``amp.init()`` installs a
process-wide *cast policy* consulted by the single op dispatcher
(mxnet_tpu.ndarray.ops.invoke).  Ops on the target-dtype list see their
float inputs cast to bf16/fp16 (MXU-friendly); ops on the fp32 list are
computed in fp32 (numerics-sensitive: softmax, norms, exp/log).  Because
hybridize traces through the same dispatcher, the policy bakes the casts
into the step's single XLA computation — the low_precision_pass with the
compiler doing the fusion.  Default target on TPU is bfloat16, which needs
no loss scaling; the fp16 LossScaler is kept for API/semantics parity.
"""
from __future__ import annotations

import contextlib
import threading
import warnings
from typing import Optional

import jax.numpy as jnp
import numpy as onp

from .. import base as _base
from ..ndarray import NDArray

from .lists import FP16_FUNCS, FP32_FUNCS, WIDEST_TYPE_CASTS

__all__ = ["init", "init_trainer", "scale_loss", "unscale",
           "convert_model", "convert_hybrid_block", "LossScaler",
           "current_policy", "amp_cast", "amp_multicast"]

_state = threading.local()


class _Policy:
    def __init__(self, target_dtype):
        self.target_dtype = jnp.dtype(target_dtype)
        self.target_ops = set(FP16_FUNCS)
        self.fp32_ops = set(FP32_FUNCS)

    def cast_args(self, opname, arrs):
        if opname in self.target_ops:
            return tuple(
                a.astype(self.target_dtype)
                if a.dtype in (jnp.float32, jnp.float64) else a
                for a in arrs)
        if opname in self.fp32_ops:
            return tuple(
                a.astype(jnp.float32)
                if a.dtype in (jnp.bfloat16, jnp.float16) else a
                for a in arrs)
        return arrs


def current_policy() -> Optional[_Policy]:
    return getattr(_state, "policy", None)


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    """Enable AMP (parity: amp.init).  Default bf16 on TPU."""
    if str(target_dtype) in ("float16", "fp16"):
        target_dtype = "float16"
    else:
        target_dtype = "bfloat16"
    p = _Policy(target_dtype)
    if target_precision_ops:
        p.target_ops |= set(target_precision_ops)
    if fp32_ops:
        p.fp32_ops |= set(fp32_ops)
    _state.policy = p
    return p


def reset():
    _state.policy = None


class LossScaler:
    """Dynamic loss scaling (parity: contrib/amp/loss_scaler.py).  Needed
    for fp16 only; bf16 runs unscaled."""

    def __init__(self, init_scale=2.0 ** 16, scale_factor=2.0,
                 scale_window=2000):
        self.loss_scale = init_scale
        self._scale_factor = scale_factor
        self._scale_window = scale_window
        self._unskipped = 0

    def has_overflow(self, params) -> bool:
        for p in params:
            g = p.grad() if callable(getattr(p, "grad", None)) else p.grad
            if g is None:
                continue
            a = g.asnumpy() if isinstance(g, NDArray) else onp.asarray(g)
            if not onp.isfinite(a).all():
                return True
        return False

    def update_scale(self, skip: bool):
        if skip:
            self.loss_scale = max(1.0, self.loss_scale / self._scale_factor)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._scale_window:
                self.loss_scale *= self._scale_factor
                self._unskipped = 0


_warned_no_scaler = False


def _warn_no_scaler(fn_name: str):
    """The historical behaviour of scale_loss/unscale without an
    attached scaler was a SILENT no-op — deprecation path: warn once so
    the user learns their fp16 run is training unscaled."""
    global _warned_no_scaler
    if not _warned_no_scaler:
        _warned_no_scaler = True
        warnings.warn(
            f"amp.{fn_name} called on a trainer with no LossScaler "
            "attached: this is a no-op (the loss is NOT being scaled). "
            "Call amp.init_trainer(trainer) first — the silent no-op "
            "path is deprecated and will become an error.",
            FutureWarning, stacklevel=3)


def init_trainer(trainer, loss_scaler: Optional["LossScaler"] = None):
    """Attach a LossScaler to a trainer (parity: amp.init_trainer).

    gluon ``Trainer``: the scaler is consulted eagerly — ``step()``
    skips the update and shrinks the scale when gradients overflowed.
    ``ShardedTrainer``: the scaler's *schedule* compiles into the jitted
    step (scale/unscale/skip/grow all in-graph; see docs/guardrails.md),
    so attach before the first ``build()``/``step()``.
    """
    scaler = loss_scaler or LossScaler()
    attach = getattr(trainer, "attach_loss_scaler", None)
    if attach is not None:           # ShardedTrainer's in-graph path
        attach(scaler)
    else:
        trainer._amp_loss_scaler = scaler
        trainer._amp_original_scale = getattr(trainer, "_scale", 1.0)
    return trainer


@contextlib.contextmanager
def scale_loss(loss, trainer):
    """Scale the loss before backward; trainer.step unscales
    (parity: amp.scale_loss).

    With a ``ShardedTrainer`` the scaling already happens inside the
    compiled step, so this yields the loss unchanged (kept so training
    scripts are portable across the two trainers)."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        _warn_no_scaler("scale_loss")
        yield loss
        return
    if getattr(trainer, "attach_loss_scaler", None) is not None:
        yield loss                   # sharded path scales in-graph
        return
    if isinstance(loss, (list, tuple)):
        yield [l * scaler.loss_scale for l in loss]
    else:
        yield loss * scaler.loss_scale
    # after backward: trainer must divide grads by the scale
    trainer._scale = getattr(trainer, "_amp_original_scale", 1.0) / \
        scaler.loss_scale


def unscale(trainer):
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        _warn_no_scaler("unscale")
        return
    if getattr(trainer, "attach_loss_scaler", None) is not None:
        return                       # sharded path unscales in-graph
    inv = 1.0 / scaler.loss_scale
    for p in trainer._params:
        g = p.grad()
        if g is not None:
            g._rebind((g.jax * inv).astype(g.jax.dtype))


def _cast_block_params(block, dtype, keep_fp32=("gamma", "beta",
                                                "running_mean",
                                                "running_var")):
    for name, p in block.collect_params().items():
        if any(name.endswith(k) for k in keep_fp32):
            continue
        if p._data is not None and p.dtype in (onp.float32,):
            p.cast(dtype)
    return block


def convert_model(net, target_dtype="bfloat16"):
    """Cast a model's compute params to the target dtype
    (parity: amp.convert_model)."""
    return _cast_block_params(net, target_dtype)


def convert_hybrid_block(net, target_dtype="bfloat16", ctx=None):
    return _cast_block_params(net, target_dtype)


# amp_cast / amp_multicast op-parity helpers (graph nodes in MXNet; plain
# functions here since casts fuse under XLA anyway)

def amp_cast(data, dtype="bfloat16"):
    return data.astype(dtype)


def amp_multicast(*data, num_outputs=None):
    dt = jnp.result_type(*[d.jax for d in data])
    return [d.astype(dt) for d in data]
