"""AMP op lists (parity: python/mxnet/contrib/amp/lists/symbol_fp16.py).

Three classes, keyed by the dispatcher op name:
- FP16_FUNCS: compute-bound ops run in the target low precision (MXU ops).
- FP32_FUNCS: numerics-sensitive ops forced to fp32.
- WIDEST_TYPE_CASTS: multi-input ops whose inputs are promoted to the widest
  participating dtype (jnp promotion already does this; listed for parity).
"""

FP16_FUNCS = [
    "dot", "batch_dot", "matmul", "FullyConnected", "Convolution",
    "Deconvolution", "RNN", "interleaved_matmul_selfatt_qk",
    "interleaved_matmul_selfatt_valatt", "linalg_gemm2",
    "dot_product_attention", "einsum", "tensordot", "inner", "outer",
    "vdot", "kron",
    # attention kernels accumulate in f32 internally; bf16 inputs feed
    # the MXU at full rate
    "flash_attention", "ring_attention", "ulysses_attention",
    "sparse_dot",
]

FP32_FUNCS = [
    "softmax", "log_softmax", "softmax_cross_entropy", "softmin",
    "BatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm",
    "L2Normalization", "norm", "exp", "expm1", "log", "log1p", "log2",
    "log10", "power", "rsqrt", "rcbrt", "erfinv", "gamma", "gammaln",
    "cosh", "sinh", "tan", "arccosh", "arcsinh", "arctanh", "mean", "sum",
    "nansum", "prod", "nanprod", "cumsum", "cumprod", "var", "std",
    "smooth_l1", "quantile", "logaddexp", "logaddexp2", "logsumexp",
    "LRN", "SoftmaxActivation", "masked_softmax", "masked_log_softmax",
    "moments", "linalg_det", "linalg_inverse", "linalg_slogdet",
    "linalg_potrf", "linalg_trsm", "linalg_syrk",
]

WIDEST_TYPE_CASTS = [
    "add", "subtract", "multiply", "divide", "maximum", "minimum", "mod",
    "hypot", "arctan2", "where", "concat", "concatenate", "stack",
    "broadcast_add", "broadcast_sub", "broadcast_mul", "broadcast_div",
]
