"""`FleetRouter` — the multi-replica front door.

One :class:`~mxnet_tpu.serving.InferenceEngine` is not a fleet: heavy
traffic needs N replicas, and the router is the tier that coordinates
them while each replica keeps its single-engine semantics.  Callers
swap one import — the router exposes the same ``infer`` / ``submit`` /
``stats`` / ``stop`` surface as the engine — and get:

- **Prefix-affinity placement** (:mod:`.policy`): requests sharing a
  system prompt rendezvous-hash onto the replica whose prefix pool
  already holds that prompt's K/V, multiplying the single-engine TTFT
  win (docs/serving.md) across the fleet instead of paying one full
  prefill per replica per prompt family.  A saturated affinity target
  spills to the least-loaded healthy replica — a prefix hit is not
  worth queueing behind a hot spot.
- **Health-gated placement** (:mod:`.replica`): a monitor thread polls
  every replica's ``health()``; a dead/condemned replica stops taking
  traffic immediately and is re-admitted only after a probation window
  with exponential backoff — rebuilt fresh via the engine ``factory``
  (a condemned engine cannot be restarted) and re-warmed so it never
  compiles on live traffic.
- **Gray-failure ejection** (docs/integrity.md): binary liveness misses
  the replica that answers ``health()`` but serves 10x slow.  The
  router feeds each completion's latency into the owning replica's
  :class:`~mxnet_tpu.resilience.integrity.LatencyTracker`; the monitor
  compares EWMA + windowed p99 against the median of its PEERS
  (self-excluded, so an outlier cannot inflate its own bar) and moves
  outliers (``gray_multiplier`` above that median, with
  ``gray_min_samples`` evidence) to ``SUSPECT`` — HRW-skipped like a
  dead replica but still finishing its in-flight work, re-admitted
  through the probation/backoff ladder WITHOUT a rebuild once its
  window clears (warm caches: zero compiles on re-admission).  SUSPECT
  is never saturation evidence: a gray replica can slow the fleet, it
  must not talk it into a coordinated brownout.
- **Failover**: a request failed by a crashed or stopped replica is
  resubmitted to a healthy one — within the request's ORIGINAL
  deadline (the clock is never reset) and a bounded per-request
  failover budget (never refreshed by a resubmission), so a poisoned
  request cannot ping-pong around the fleet forever.  With
  ``hedge_after`` set, a request stuck past that long on its primary
  is duplicated onto a second replica and the first completion wins
  (greedy decode is deterministic, so duplicates agree).
- **Rolling drain/restart**: ``drain(name)`` quiesces one replica
  through the engine's SIGTERM drain path while traffic steers away;
  ``restart(name)`` rebuilds it; ``rolling_restart()`` chains both
  across the fleet for zero-downtime upgrades.  ``stop()`` drains ALL
  replicas concurrently under one deadline, and a replica that hangs
  in drain is condemned (watchdog-killed) rather than wedging fleet
  shutdown.

Fault-injection sites (docs/resilience.md): ``fleet.route`` (before
affinity-key computation — faults degrade to least-loaded placement),
``fleet.failover`` (before a resubmission — faults abort that failover
attempt), ``fleet.drain`` (per-replica shutdown worker — a delay here
models a replica hanging in drain, which the stop deadline must
condemn).

Observability: every replica engine already exports per-engine labeled
series (unique ``engine=`` names); the router adds a ``fleet:<name>``
collector with routing/failover/lifecycle counters, per-replica
up/routed series, and the fleet-aggregated prefix hit rate, all under
``mxtpu_fleet_*`` names in the same process-wide ``collect()``.
"""
from __future__ import annotations

import collections
import random as _pyrandom
import signal as _signal
import statistics as _statistics
import threading
import time
import weakref
from typing import Callable, List, Optional, Sequence, Set, Tuple

import numpy as onp

from ..analysis.lockwitness import (named_lock as _named_lock,
                                    note_blocking as _note_blocking)
from ..observability.flightrecorder import active as _fr_active
from ..resilience.faults import inject as _inject
from ..serving.errors import (DeadlineInfeasibleError, EngineCrashedError,
                              EngineStoppedError, FleetSaturatedError,
                              InvalidRequestError, NoHealthyReplicaError,
                              QueueFullError, RequestCancelledError,
                              RequestTimeoutError, ServingError)
from ..serving.overload import CircuitBreaker, RetryBudget
from .directory import FleetDirectory
from .policy import RoutingPolicy
from .replica import (DEAD, DRAINING, HEALTHY, STOPPED, SUSPECT,
                      ReplicaHandle)

__all__ = ["FleetRouter", "FleetFuture"]


class _FleetRequest:
    """Replica-independent request record — everything needed to
    resubmit the request to another replica on failover."""

    __slots__ = ("payload", "kind", "max_new_tokens", "eos_id", "deadline",
                 "failovers_left", "priority", "sampling")

    def __init__(self, payload, kind, max_new_tokens, eos_id, deadline,
                 failovers, priority=None, sampling=None):
        self.payload = payload
        self.kind = kind
        self.max_new_tokens = max_new_tokens
        self.eos_id = eos_id
        self.deadline = deadline          # absolute monotonic, never reset
        self.failovers_left = failovers   # never refreshed
        self.priority = priority          # QoS class, carried on failover
        # per-request sampling params (docs/serving.md), carried on
        # every failover/hedge attempt: draws fold the request seed
        # with ABSOLUTE token positions, so a resubmitted request
        # reproduces the same stream on any replica
        self.sampling = sampling or {}

    def remaining(self, now: Optional[float] = None) -> Optional[float]:
        if self.deadline is None:
            return None
        now = time.monotonic() if now is None else now
        return self.deadline - now


class FleetFuture:
    """The router-side future: resolves like an engine future, but a
    replica-level failure (``EngineCrashedError`` / ``EngineStoppedError``)
    — or a queued attempt priority-EVICTED by that replica
    (``QueueFullError`` on the inner future, docs/overload.md) —
    triggers failover instead of surfacing — the caller only ever sees
    a result, a request-level typed error, or a fleet-level typed error
    once budget/deadline/replicas are exhausted.  ``trace_id`` follows
    the CURRENT attempt (each engine submit allocates its own)."""

    def __init__(self, router: "FleetRouter", req: _FleetRequest,
                 handle: ReplicaHandle, inner):
        self._router = router
        self._req = req
        self._lock = _named_lock("fleet.future",
                                 "per-request attempt list")
        self._attempts: List[Tuple[ReplicaHandle, object]] = [(handle, inner)]
        self._exc: Optional[BaseException] = None   # terminal failure
        self._hedged = False
        self._t_submit = time.monotonic()
        # per-ATTEMPT submit stamps: the completion latency fed to the
        # gray-failure tracker (docs/integrity.md) must charge each
        # replica only for ITS attempt, not the whole failover chain
        self._attempt_t = {inner: self._t_submit}
        self._observed = False
        self.trace_id = inner.trace_id

    def _observe(self, handle: ReplicaHandle, fut) -> None:
        """Feed the winning attempt's server-side latency (submit →
        ``t_done``) to its replica's tracker, exactly once per request —
        repeat ``result()`` calls and concurrent waiters must not
        multiply one completion into several samples."""
        with self._lock:
            if self._observed:
                return
            self._observed = True
            t0 = self._attempt_t.get(fut)
        if t0 is None:
            return
        t1 = fut.t_done if getattr(fut, "t_done", None) is not None \
            else time.monotonic()
        self._router._observe_completion(handle, max(0.0, t1 - t0))

    def done(self) -> bool:
        """True once ANY attempt has resolved (a hint for pollers; a
        done-with-replica-failure attempt still fails over inside
        ``result()``) or the request failed terminally."""
        with self._lock:
            return self._exc is not None or \
                any(f.done() for _h, f in self._attempts)

    def result(self, timeout: Optional[float] = None):
        _note_blocking("fleet.future_wait")
        client_deadline = None if timeout is None else \
            time.monotonic() + timeout
        while True:
            with self._lock:
                if self._exc is not None:
                    # terminal (failover exhausted / deadline blown):
                    # repeat calls — or a second waiting thread — see
                    # the same typed error, like an engine future
                    raise self._exc
                attempts = list(self._attempts)
            primary_h, primary_f = attempts[0]
            # resolve any DONE attempt first (a hedge may beat the
            # primary); otherwise block a short chunk on the primary so
            # the common single-attempt path costs no busy-wait
            ready = [(h, f) for h, f in attempts if f.done()]
            if not ready:
                chunk = 0.05
                if client_deadline is not None:
                    chunk = min(chunk, max(0.0, client_deadline
                                           - time.monotonic()))
                try:
                    val = primary_f.result(chunk)
                except TimeoutError:
                    val, ready = None, []
                except RequestCancelledError:
                    # this attempt lost a hedge race and was reaped —
                    # re-snapshot; the winner resolves next iteration
                    continue
                except DeadlineInfeasibleError:
                    raise      # admission-time reject: not latency evidence
                except RequestTimeoutError:
                    # a request the replica held past its deadline IS
                    # latency evidence — without this, a replica slow
                    # enough that everything times out would feed the
                    # gray detector nothing and keep its keyspace
                    self._observe(primary_h, primary_f)
                    raise
                except (EngineCrashedError, EngineStoppedError,
                        QueueFullError) as e:
                    # QueueFullError on a QUEUED future = the attempt
                    # was priority-EVICTED by a higher-class arrival on
                    # that replica (docs/overload.md) — a replica-local
                    # capacity decision, not the request's fault:
                    # re-place it elsewhere within the same failover /
                    # retry-budget / deadline bounds
                    self._drop_attempt(primary_h, primary_f, e)
                    continue
                else:
                    self.trace_id = primary_f.trace_id
                    self._observe(primary_h, primary_f)
                    self._reap_losers(primary_f)
                    return val
            for h, f in ready:
                try:
                    val = f.result(0)
                except TimeoutError:      # raced: no longer done — retry
                    continue
                except RequestCancelledError:
                    continue   # reaped hedge loser — the winner is
                               # also in (or about to enter) ready
                except DeadlineInfeasibleError:
                    raise      # admission-time reject: not latency evidence
                except RequestTimeoutError:
                    self._observe(h, f)   # held past deadline = evidence
                    raise
                except (EngineCrashedError, EngineStoppedError,
                        QueueFullError) as e:
                    self._drop_attempt(h, f, e)
                    break
                else:
                    self.trace_id = f.trace_id
                    self._observe(h, f)
                    self._reap_losers(f)
                    return val
            if ready:
                continue
            now = time.monotonic()
            if client_deadline is not None and now >= client_deadline:
                raise TimeoutError(
                    "result() wait timed out (the request may still "
                    "complete fleet-side)")
            self._maybe_hedge(now)

    def _reap_losers(self, winner) -> None:
        """Hedged-request cleanup (docs/overload.md): the first copy
        to complete wins; every OTHER in-flight attempt is actively
        cancelled — dequeued if still queued, its KV slot flagged
        reclaimable if mid-decode — instead of running to completion
        as pure waste.  Each attempt that was still live counts one
        ``hedges_wasted``.  Losers leave ``_attempts`` BEFORE their
        futures can resolve with ``RequestCancelledError``, so a repeat
        ``result()`` call (or a concurrent waiter) only ever sees the
        winner."""
        with self._lock:
            losers = [(h, f) for h, f in self._attempts if f is not winner]
            self._attempts[:] = [(h, f) for h, f in self._attempts
                                 if f is winner]
        for h, f in losers:
            try:
                if h.engine.cancel(f):
                    self._router._count("hedges_wasted")
            except Exception:
                pass               # cleanup is best-effort, never fatal

    def _drop_attempt(self, handle, fut, exc):
        """One attempt died with a REPLICA-level error (crash, stop,
        or queue eviction): if other (hedged) attempts are still in
        flight, just forget this one; otherwise fail over — the router
        resubmits within the request's budget and deadline, or
        re-raises."""
        if isinstance(exc, EngineCrashedError):
            # blame the engine that actually crashed: a disaggregated
            # request routed to a PREFILL replica can die on the DECODE
            # replica that adopted it — marking the routed handle dead
            # would execute the wrong replica (and, with one prefill
            # replica, take the whole admission path down with it)
            src = getattr(exc, "engine", None)
            victim = handle if src is None or src == handle.name \
                else self._router._by_name.get(src)
            if victim is not None and victim.mark_dead(str(exc)):
                self._router._replica_death(victim, str(exc))
        elif isinstance(exc, QueueFullError):
            # the replica shed queued work under pressure — same
            # breaker signal as a shed at submit
            handle.breaker.record_failure()
        with self._lock:
            try:
                self._attempts.remove((handle, fut))
            except ValueError:
                pass
            alive = bool(self._attempts)
        if alive:
            return
        if isinstance(exc, QueueFullError):
            # counted only when the eviction actually triggers a
            # failover attempt — a hedged sibling still in flight means
            # the drop is just forgotten, and the counter must
            # reconcile against `failovers` during incidents
            self._router._count("eviction_failovers")
        try:
            nxt = self._router._failover(self._req, exc)
        except BaseException as e:
            with self._lock:
                self._exc = e       # terminal: _attempts is empty now
            raise
        with self._lock:
            self._attempts.append(nxt)
            self._attempt_t[nxt[1]] = time.monotonic()

    def _maybe_hedge(self, now: float):
        r = self._router
        if r.hedge_after is None or self._hedged:
            return
        if now - self._t_submit < r.hedge_after:
            return
        self._hedged = True
        # a hedge is fleet-added retry load: it must fit the retry
        # budget or be skipped — hedging during an overload is exactly
        # the thundering-herd amplifier the budget exists to cap
        if not r._retry_budget.try_acquire(now=now):
            r._count("retry_budget_exhausted")
            return
        with self._lock:
            exclude = {h.name for h, _f in self._attempts}
        try:
            nxt = r._submit_once(self._req, exclude=exclude)
        except ServingError:
            # hedging is an optimization, never fatal — and a hedge
            # that placed NOTHING added no retry load, so its token
            # goes back (shed probes are O(admission check), not work)
            r._retry_budget.refund()
            return
        r._count("hedges")
        with self._lock:
            self._attempts.append(nxt)
            self._attempt_t[nxt[1]] = time.monotonic()


class FleetRouter:
    """Front N engine replicas behind the single-engine surface.

    Parameters
    ----------
    engines : existing engines to wrap, one replica each (their claimed
        ``name`` becomes the replica name).  Dead replicas can only be
        re-admitted when a ``factory`` is also given.
    factory : ``factory(replica_name) -> InferenceEngine`` — builds a
        replica.  With ``num_replicas`` (and no ``engines``) the router
        builds the initial fleet ``<name>-r0 … <name>-r{N-1}`` itself;
        it is also how a dead replica is rebuilt after probation and
        how ``restart()`` works.  Pass ``name=replica_name`` through to
        the engine so metrics labels follow the replica.
    num_replicas : fleet size when building from ``factory``.
    routing : ``'affinity'`` (default — prefix-affinity with
        least-loaded spill), ``'least_loaded'``, or ``'random'``
        (seeded; the control arm for the fleet benchmark).
    affinity_min_tokens / affinity_window / tracker_entries : the
        :class:`~.policy.RoutingPolicy` knobs.
    spill_queue_depth : affinity target counts as SATURATED when its
        admission queue is at least this deep (default: 2x the first
        engine's ``num_slots``) — the spill trades a prefix hit for not
        queueing behind a hot replica.
    max_failovers : per-request budget of crash-failover resubmissions
        (the fleet-level analogue of the engine's per-request step-retry
        budget; never refreshed by a failover).
    hedge_after : seconds after which a still-unresolved request is
        duplicated onto a second healthy replica (None = no hedging).
        The winning copy actively CANCELS the loser (dequeue, or slot
        reclaim mid-decode) — counted as ``hedges_wasted``.
    retry_budget_rate / retry_budget_burst : token bucket bounding
        fleet-ADDED retry load (docs/overload.md): every failover
        resubmission and every hedge spends a token; an empty bucket
        surfaces the original failure typed (failover) or skips the
        hedge, so a replica crash during saturation cannot amplify
        into a thundering herd.
    breaker_threshold / breaker_cooldown : per-replica circuit breaker
        — that many consecutive sheds / replica-level submit failures
        stop the router offering the replica traffic for the cooldown,
        then half-open with a probe.
    saturation_threshold / saturation_window / saturation_brownout :
        coordinated brownout — that many all-replicas-shed submits
        within the window force every replica's overload controller to
        its brownout floor (``engine.force_brownout()``), and the
        caller sees the typed :class:`FleetSaturatedError` (a
        ``QueueFullError`` subclass) instead of an opaque shed.
    health_interval : monitor poll period in seconds.
    gray_multiplier / gray_min_samples / gray_window : gray-failure
        ejection (docs/integrity.md): a HEALTHY replica whose
        completion-latency EWMA *and* windowed p99 are at least
        ``gray_multiplier`` times its peer median (median of the OTHER
        eligible replicas' EWMAs — self-excluded so an outlier cannot
        inflate its own bar; at least two replicas with
        ``gray_min_samples`` completions in their
        ``gray_window``-sample windows) goes
        ``SUSPECT`` — unroutable but alive, re-admitted without rebuild
        after the probation ladder's window.  ``gray_ejection=False``
        disables the detector (the trackers still feed, for the
        per-replica latency gauges).
    probation / probation_backoff / probation_max : re-admission window
        after a replica death: ``probation * backoff**(deaths-1)``
        seconds, capped.  Gray suspensions ride the same ladder, keyed
        on consecutive suspect ejections.
    restart_warmup : re-run ``warmup()`` on rebuilt/restarted replicas
        so re-admission never compiles on live traffic.
    drain_timeout : default deadline for ``stop()`` / the SIGTERM drain
        (None = wait indefinitely; a hung replica still cannot wedge
        shutdown forever — its engine watchdog or an explicit timeout
        condemns it).
    name : fleet name — the ``fleet=`` label on every ``mxtpu_fleet_*``
        series and the default prefix for factory-built replica names.
    """

    def __init__(self, engines: Optional[Sequence] = None, *,
                 factory: Optional[Callable] = None,
                 num_replicas: Optional[int] = None,
                 routing: str = "affinity",
                 affinity_min_tokens: int = 4,
                 affinity_window: int = 32,
                 tracker_entries: int = 512,
                 spill_queue_depth: Optional[int] = None,
                 max_failovers: int = 2,
                 hedge_after: Optional[float] = None,
                 retry_budget_rate: float = 2.0,
                 retry_budget_burst: int = 8,
                 breaker_threshold: int = 5,
                 breaker_cooldown: float = 0.5,
                 saturation_threshold: int = 3,
                 saturation_window: float = 1.0,
                 saturation_brownout: bool = True,
                 gray_ejection: bool = True,
                 gray_multiplier: float = 4.0,
                 gray_min_samples: int = 12,
                 gray_window: int = 64,
                 health_interval: float = 0.05,
                 probation: float = 0.25,
                 probation_backoff: float = 2.0,
                 probation_max: float = 30.0,
                 restart_warmup: bool = True,
                 drain_timeout: Optional[float] = None,
                 seed: int = 0,
                 name: str = "fleet"):
        if routing not in ("affinity", "least_loaded", "random"):
            raise ServingError(f"routing must be 'affinity'|'least_loaded'|"
                             f"'random', got {routing!r}")
        self.name = str(name)
        self.routing = routing
        self.factory = factory
        self.max_failovers = int(max_failovers)
        self.hedge_after = hedge_after
        self.health_interval = float(health_interval)
        self.drain_timeout = drain_timeout
        # retry-storm protection (docs/overload.md)
        self._retry_budget = RetryBudget(retry_budget_rate,
                                         retry_budget_burst)
        self._breaker_threshold = int(breaker_threshold)
        self._breaker_cooldown = float(breaker_cooldown)
        self.saturation_threshold = int(saturation_threshold)
        self.saturation_window = float(saturation_window)
        self.saturation_brownout = bool(saturation_brownout)
        # gray-failure defense (docs/integrity.md)
        self.gray_ejection = bool(gray_ejection)
        self.gray_multiplier = float(gray_multiplier)
        self.gray_min_samples = int(gray_min_samples)
        self.gray_window = int(gray_window)
        self._sat_lock = _named_lock("fleet.router.saturation",
                                     "all-replicas-shed event window")
        # last `saturation_threshold` all-replicas-shed event times
        self._sat_times = collections.deque(
            maxlen=max(1, self.saturation_threshold))
        self._sat_brownout_at = -1e9
        self._policy = RoutingPolicy(affinity_min_tokens, affinity_window,
                                     tracker_entries)
        self._rng = _pyrandom.Random(int(seed))
        self._rng_lock = _named_lock("fleet.router.rng",
                                     "seeded routing tiebreak RNG")

        if engines is None:
            if factory is None or not num_replicas:
                raise ServingError(
                    "FleetRouter needs engines=[...] or factory= + "
                    "num_replicas=N")
            engines = [factory(f"{self.name}-r{i}")
                       for i in range(int(num_replicas))]
            names = [f"{self.name}-r{i}" for i in range(int(num_replicas))]
        else:
            engines = list(engines)
            if not engines:
                raise ServingError("FleetRouter needs at least one replica")
            names = [e.name for e in engines]
        if len(set(names)) != len(names):
            raise ServingError(f"replica names must be unique, got {names}")
        mode = engines[0].mode
        if any(e.mode != mode for e in engines):
            raise ServingError("all replicas must share one mode "
                               "(decode or forward)")
        self.mode = mode
        # kept for elastic scale-up: a newcomer's handle must ride the
        # same probation/backoff/warmup contract as the founders
        self._handle_kw = dict(probation=probation,
                               probation_backoff=probation_backoff,
                               probation_max=probation_max,
                               restart_warmup=restart_warmup,
                               latency_window=self.gray_window)
        self._handles = [
            ReplicaHandle(n, e, factory=factory,
                          breaker=CircuitBreaker(self._breaker_threshold,
                                                 self._breaker_cooldown),
                          **self._handle_kw)
            for n, e in zip(names, engines)]
        self._by_name = {h.name: h for h in self._handles}
        # serializes scale_up/scale_down against each other and against
        # drain/stop; routing threads read _handles/_by_name without it
        # (mutation is copy-then-atomic-reassign, never in place)
        self._scale_lock = _named_lock("fleet.router.scale",
                                       "elastic membership changes")
        self._scale_seq = len(self._handles)
        self.spill_queue_depth = int(spill_queue_depth) \
            if spill_queue_depth is not None \
            else max(2, 2 * engines[0].num_slots)
        # fleet-wide prefix/page directory (docs/fleet.md
        # "Disaggregated serving"): affinity key -> the replica whose
        # pool actually HOLDS that family's KV.  Consulted ahead of the
        # stateless HRW rank for both unified placement and migrated
        # decode placement; published wherever residency is created.
        self._directory = FleetDirectory(tracker_entries)
        # disaggregated prefill/decode fleet: any replica carrying a
        # non-unified role splits placement two-stage — new requests go
        # to prefill-capable replicas by load, and each prefill-role
        # engine's migration egress is wired into the router's decode
        # placement (directory affinity, then HRW, then load)
        self.disaggregated = any(h.role != "unified"
                                 for h in self._handles)
        if self.disaggregated:
            if self.mode != "decode":
                raise ServingError(
                    "disaggregated roles are a decode-mode concept; "
                    "this fleet serves forward mode")
            if not any(h.can_prefill() for h in self._handles):
                raise ServingError(
                    "disaggregated fleet has no prefill-capable "
                    "replica (role='prefill' or 'unified') — nothing "
                    "could ever accept a request")
            if not any(h.can_decode() for h in self._handles):
                raise ServingError(
                    "disaggregated fleet has no decode-capable replica "
                    "(role='decode' or 'unified') — every handoff "
                    "would fall back colocated")
            for h in self._handles:
                self._wire_migration(h)

        self._counters = {}
        self._counters_lock = _named_lock("fleet.router.counters",
                                          "fleet counter map")
        self._mon_stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self._stop_lock = _named_lock("fleet.router.stop",
                                      "stop()/drain mutual exclusion")
        self._stopping = False
        self._prev_handlers = None
        self._register_collector()

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "FleetRouter":
        if self._monitor is not None:
            raise ServingError("router already started")
        if self._stopping:  # raceguard: unguarded(one-way stop flag: atomic bool read; the stop path itself serializes under _stop_lock)
            raise ServingError("router cannot be restarted once stopped "
                               "— build a fresh FleetRouter")
        for h in self._members:
            if h.engine._thread is None:
                h.engine.start()
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         name="mxnet_tpu-fleet-monitor",
                                         daemon=True)
        self._monitor.start()
        return self

    def warmup(self, **kw) -> dict:
        """Pre-compile every replica's lattice; returns
        ``{replica_name: programs_compiled}``.  After this, each
        replica's ``compiles`` counter must stay frozen on traffic —
        the same contract as the single engine."""
        return {h.name: h.engine.warmup(**kw) for h in self._members}

    def __enter__(self):
        if self._monitor is None:
            self.start()
        return self

    def __exit__(self, *exc):
        self.stop(drain=not any(exc))

    def stop(self, drain: bool = True, timeout: Optional[float] = None):
        """Stop the fleet: every replica drains CONCURRENTLY (a fleet
        of N must not pay N serial drains) under one deadline
        (``timeout``, default ``drain_timeout``).  A replica whose
        drain outlives the deadline — hung scheduler, injected
        ``fleet.drain`` delay — is CONDEMNED (its queued/in-flight
        requests fail typed, exactly the watchdog contract) rather than
        wedging shutdown.  Nothing is silently dropped: each engine's
        own stop/sweep guarantees carry over per replica."""
        with self._stop_lock:
            self._stopping = True
            self._mon_stop.set()
            mon = self._monitor
            if mon is not None and mon.is_alive() and \
                    mon is not threading.current_thread():
                mon.join(2.0)
            timeout = self.drain_timeout if timeout is None else timeout
            deadline = None if timeout is None else \
                time.monotonic() + float(timeout)
            workers = []
            for h in self._members:
                with h._lock:
                    if h.state in (HEALTHY, DRAINING, SUSPECT):
                        # SUSPECT replicas drain too: slow, not dead —
                        # their in-flight work still deserves the drain
                        h.state = DRAINING
                    elif h.state == STOPPED:
                        continue
                t = threading.Thread(
                    target=self._shutdown_replica, args=(h, drain, deadline),
                    name=f"mxnet_tpu-fleet-drain-{h.name}", daemon=True)
                t.start()
                workers.append((h, t))
            for h, t in workers:
                budget = None if deadline is None else \
                    max(0.0, deadline - time.monotonic())
                t.join(budget)
            for h, t in workers:
                if t.is_alive():
                    # the drain worker itself is stuck (e.g. a delay at
                    # fleet.drain): condemn from here — the worker's own
                    # engine.stop() then returns promptly on the crashed
                    # path and the futures are already failed typed
                    self._count("forced_stops")
                    try:
                        h.engine.condemn(
                            "fleet stop deadline exceeded — replica drain "
                            "did not complete in time")
                    except Exception:
                        pass
                    with h._lock:
                        h.state = STOPPED
            self.uninstall_signal_handlers()

    def _shutdown_replica(self, h: ReplicaHandle, drain: bool,
                          deadline: Optional[float]):
        try:
            _inject("fleet.drain")
        except BaseException:
            # an injected drain fault: the graceful path is broken, go
            # straight to the force-stop path rather than aborting the
            # shutdown of this replica
            self._count("drain_faults")
            drain = False
        budget = None if deadline is None else \
            max(0.1, deadline - time.monotonic())
        try:
            h.engine.stop(drain=drain, timeout=budget)
        except ServingError:
            # still draining at its deadline: watchdog-kill the replica
            # instead of wedging the fleet — condemnation fails every
            # queued/in-flight request typed, then the engine's stop
            # path returns promptly
            self._count("forced_stops")
            try:
                h.engine.condemn("fleet drain deadline exceeded — "
                                 "force-stopping replica")
                h.engine.stop(drain=False, timeout=2.0)
            except Exception:
                pass
        except Exception:
            pass
        with h._lock:
            h.state = STOPPED

    # ------------------------------------------------------ rolling drain
    def drain(self, replica: str, timeout: Optional[float] = None):
        """Quiesce ONE replica: new traffic steers away immediately,
        queued and in-flight requests on it complete (the SIGTERM drain
        path), then the engine stops.  The replica ends ``STOPPED`` —
        ``restart()`` brings it back.  A drain that outlives ``timeout``
        condemns the replica (see ``stop()``)."""
        if self._stopping:  # raceguard: unguarded(one-way stop flag: atomic bool read; the stop path itself serializes under _stop_lock)
            raise ServingError("fleet router is stopped")
        h = self._require(replica)
        with h._lock:
            if h.state not in (HEALTHY, SUSPECT):
                raise ServingError(f"replica {replica!r} is {h.state}, "
                                   "not drainable")
            h.state = DRAINING
            # flag the drain as DELIBERATE: the autoscaler must not
            # read this replica as shrink headroom, nor its rising
            # queue as saturation evidence (docs/fleet.md "Elastic
            # fleet" — the drain-vs-autoscaler race)
            h.manual_drain = True
        self._count("drains")
        deadline = None if timeout is None else time.monotonic() + timeout
        self._shutdown_replica(h, True, deadline)

    def restart(self, replica: str) -> bool:
        """Rebuild a drained/dead replica via the factory (fresh engine
        under the same replica name, re-warmed) and return it to
        traffic."""
        if self._stopping:  # raceguard: unguarded(one-way stop flag: atomic bool read; the stop path itself serializes under _stop_lock)
            raise ServingError("fleet router is stopped")
        h = self._require(replica)
        if h.factory is None:
            raise ServingError("restart() needs an engine factory — "
                               "construct the FleetRouter with factory=")
        if h.state == HEALTHY:
            raise ServingError(f"replica {replica!r} is healthy — drain "
                               "it first")
        if not h.rebuild():
            raise ServingError(f"replica {replica!r} rebuild failed: "
                               f"{h.last_error}")
        h.manual_drain = False
        self._wire_migration(h)
        self._count("restarts")
        return True

    def rolling_restart(self, timeout: Optional[float] = None):
        """Zero-downtime fleet restart: drain + rebuild each replica in
        sequence while the rest keep serving."""
        for h in list(self._members):
            self.drain(h.name, timeout=timeout)
            self.restart(h.name)

    def replace(self, replica: str, engine) -> None:
        """Swap a fresh, caller-built engine into a non-healthy replica
        slot (the no-factory escape hatch)."""
        h = self._require(replica)
        if h.state == HEALTHY:
            raise ServingError(f"replica {replica!r} is healthy — drain "
                               "it first")
        if engine._thread is None:
            engine.start()
        with h._lock:
            h.engine = engine
            h.state = HEALTHY
            h.restarts += 1
            h.probation_until = None
            h.suspect_until = None
            h.manual_drain = False
        h.latency.reset()

    # Membership is copy-on-write: scale_up/scale_down build a NEW
    # list/dict under _scale_lock and reassign the reference, so a
    # lock-free reader sees either the old or the new membership —
    # never a half-built one — and a stale snapshot is benign (routing
    # re-checks replica state; stats lag at most one scaling action).
    # Every lock-free read goes through these two accessors so the
    # contract lives in exactly one place.
    @property
    def _members(self) -> List[ReplicaHandle]:
        return self._handles  # raceguard: unguarded(copy-on-write membership: writers reassign a fresh list under _scale_lock; a reference read is atomic and a stale snapshot benign)

    @property
    def _name_map(self) -> dict:
        return self._by_name  # raceguard: unguarded(copy-on-write membership: writers reassign a fresh dict under _scale_lock; a reference read is atomic and a stale snapshot benign)

    def _require(self, replica: str) -> ReplicaHandle:
        h = self._name_map.get(replica)
        if h is None:
            raise ServingError(f"unknown replica {replica!r} — have "
                               f"{sorted(self._name_map)}")
        return h

    # ------------------------------------------------------ elastic scaling
    def draining(self) -> List[str]:
        """Replicas currently in a DELIBERATE drain (manual ``drain()``
        / ``rolling_restart()`` in flight) — the autoscaler holds its
        decisions while one exists: the shrinking fleet and the
        victim's rising queue are expected, not evidence."""
        return [h.name for h in self._members
                if h.manual_drain and h.state in (DRAINING, STOPPED)]

    def _next_replica_name(self) -> str:  # guarded-by: _scale_lock
        while True:
            name = f"{self.name}-r{self._scale_seq}"
            self._scale_seq += 1
            if name not in self._by_name:
                return name

    def scale_up(self, name: Optional[str] = None,
                 signals: Optional[dict] = None) -> Optional[str]:
        """Grow the fleet by one factory-built replica (docs/fleet.md
        "Elastic fleet").  The newcomer is started and **warmed before
        it joins the routing tables**, so it never compiles on live
        traffic — the same re-warm contract as probation rebuilds.  HRW
        placement then remaps only ~1/N of the keyspace, all of it onto
        the newcomer.

        A fault injected at ``fleet.scale_up`` degrades the action to a
        counted no-op BEFORE any engine is built — the fleet is left
        exactly as it was.  Returns the new replica's name, or ``None``
        on a faulted/no-op action."""
        if self._stopping:  # raceguard: unguarded(one-way stop flag: atomic bool read; the stop path itself serializes under _stop_lock)
            raise ServingError("fleet router is stopped")
        if self.factory is None:
            raise ServingError("scale_up() needs an engine factory — "
                               "construct the FleetRouter with factory=")
        with self._scale_lock:
            try:
                _inject("fleet.scale_up")
            except BaseException:
                self._count("scale_up_faults")
                return None
            new_name = name if name is not None \
                else self._next_replica_name()
            if new_name in self._by_name:
                raise ServingError(
                    f"replica name {new_name!r} already in the fleet")
            try:
                eng = self.factory(new_name)
                if eng.mode != self.mode:
                    raise ServingError(
                        f"factory built a {eng.mode}-mode engine for a "
                        f"{self.mode}-mode fleet")
                if eng._thread is None:
                    eng.start()
                # warm BEFORE taking traffic: the compile freeze must
                # hold from the newcomer's first routed request
                eng.warmup()
            except ServingError:
                raise
            except Exception as e:
                try:
                    eng.stop(drain=False, timeout=1.0)
                except Exception:
                    pass
                self._count("scale_up_failures")
                raise ServingError(
                    f"scale_up: building replica {new_name!r} failed: "
                    f"{e!r}") from e
            if self._stopping:  # raceguard: unguarded(one-way stop flag: atomic bool read; the stop path itself serializes under _stop_lock)
                # the fleet stopped while the newcomer warmed: joining
                # now would strand a live engine no shutdown walks —
                # discard it and degrade to a counted no-op
                try:
                    eng.stop(drain=False, timeout=1.0)
                except Exception:
                    pass
                self._count("scale_up_aborts")
                return None
            h = ReplicaHandle(
                new_name, eng, factory=self.factory,
                breaker=CircuitBreaker(self._breaker_threshold,
                                       self._breaker_cooldown),
                **self._handle_kw)
            self._wire_migration(h)
            # copy-then-reassign: routing threads iterate _handles /
            # read _by_name without the scale lock, so membership must
            # flip atomically, never mutate in place
            self._by_name = {**self._by_name, new_name: h}
            self._handles = self._handles + [h]
            self._count("scale_ups")
            fr = _fr_active()
            if fr is not None:
                fr.record("fleet.scale_up", fleet=self.name,
                          replica=new_name,
                          replicas=len(self._handles),
                          **(signals or {}))
            return new_name

    def scale_down(self, replica: Optional[str] = None,
                   timeout: Optional[float] = None, reseed: bool = True,
                   signals: Optional[dict] = None) -> Optional[str]:
        """Shrink the fleet by one replica, loss-free (docs/fleet.md
        "Elastic fleet"): the victim (named, or the least-loaded
        healthy replica) stops taking new traffic immediately, its
        queued and in-flight requests DRAIN to completion, its hot
        prefix entries are exported and re-seeded onto the survivors
        (HRW-targeted per family, via the ordinary prefix-insert path —
        under paged KV a refcount-claim handoff), the fleet directory
        forgets it, and only then does it leave the membership.  Warm
        prompt families stay warm; zero requests are lost.

        A fault injected at ``fleet.scale_down`` degrades the action to
        a counted no-op BEFORE the victim starts draining — a faulted
        scale action never strands a replica half-drained.  Returns the
        removed replica's name, or ``None`` on a faulted/no-op
        action."""
        if self._stopping:  # raceguard: unguarded(one-way stop flag: atomic bool read; the stop path itself serializes under _stop_lock)
            raise ServingError("fleet router is stopped")
        with self._scale_lock:
            healthy = self._healthy()
            if replica is None:
                candidates = [h for h in healthy]
                if not candidates:
                    raise NoHealthyReplicaError(
                        f"fleet {self.name!r}: no healthy replica to "
                        f"scale down")
                h = min(candidates, key=lambda c: (c.load(), c.name))
            else:
                h = self._require(replica)
            survivors = [s for s in healthy if s is not h]
            if not survivors:
                raise ServingError(
                    f"scale_down would leave fleet {self.name!r} with "
                    f"no healthy replica — refusing")
            try:
                _inject("fleet.scale_down")
            except BaseException:
                # degrade to no-op: the victim has not been touched —
                # it keeps serving, nothing is half-drained
                self._count("scale_down_faults")
                return None
            with h._lock:
                if h.state not in (HEALTHY, SUSPECT):
                    raise ServingError(
                        f"replica {h.name!r} is {h.state}, not "
                        f"removable — scale_down wants a live victim")
                h.state = DRAINING
            # 1) loss-free drain: queued + in-flight requests complete
            #    (the SIGTERM drain path; a hang is condemned, typed)
            deadline = None if timeout is None \
                else time.monotonic() + timeout
            self._shutdown_replica(h, True, deadline)
            # 2) harvest the victim's warm families off the stopped
            #    engine (its caches are still resident; best-effort)
            seeds = []
            if reseed:
                try:
                    seeds = h.engine.export_prefix_seeds()
                except Exception:
                    seeds = []
            # 3) membership flip (atomic reassign) + the directory
            #    forgets the corpse so no placement steers at it — a
            #    stale locate degrades to directory-second placement,
            #    but why pay the typed miss at all
            self._handles = [x for x in self._handles if x is not h]
            self._by_name = {n: x for n, x in self._by_name.items()
                             if x is not h}
            forgotten = self._directory.forget_replica(h.name)
            # 4) re-seed survivors: each family lands on its HRW winner
            #    among the remaining replicas — exactly where the
            #    router will place its next member
            planted = self._reseed(seeds)
            self._count("scale_downs")
            fr = _fr_active()
            if fr is not None:
                fr.record("fleet.scale_down", fleet=self.name,
                          replica=h.name,
                          replicas=len(self._handles),
                          seeds_exported=len(seeds),
                          seeds_planted=planted,
                          directory_forgotten=forgotten,
                          **(signals or {}))
            return h.name

    def _reseed(self, seeds) -> int:
        """Plant exported prefix seeds on the survivors: HRW-target
        each family's winner first (that is where followers will
        route), spilling down the rank on refusal.  Residency is
        published to the directory wherever a seed lands, so the next
        family member gets a directory hit, not a cold miss.  Returns
        the number of seeds planted."""
        planted = 0
        for seed in seeds:
            candidates = self._healthy()
            if not candidates:
                break
            key = None
            try:
                key = self._policy.peek_key(seed.tokens)
            except Exception:
                pass
            if key is not None:
                ranked = self._policy.rank(key,
                                           [c.name for c in candidates])
                order = [self._name_map[n] for n in ranked
                         if n in self._name_map]
            else:
                order = sorted(candidates,
                               key=lambda c: (c.load(), c.name))
            for target in order:
                try:
                    if target.engine.seed_prefix(seed):
                        planted += 1
                        self._directory.publish(key, target.name)
                        break
                except ServingError:
                    continue       # typed refusal: offer the next survivor
                except Exception:
                    continue
        if planted:
            self._count("seeds_migrated", planted)
        return planted

    # --------------------------------------------------------- SIGTERM
    def install_signal_handlers(self, signals=(_signal.SIGTERM,)):
        """Route SIGTERM (the preemption notice) to a concurrent
        fleet-wide ``stop(drain=True)`` on a helper thread, bounded by
        ``drain_timeout``."""
        prev = {}
        for s in signals:
            prev[s] = _signal.signal(s, self._on_term_signal)
        self._prev_handlers = prev
        return prev

    def uninstall_signal_handlers(self):
        if self._prev_handlers and \
                threading.current_thread() is threading.main_thread():
            for s, hd in self._prev_handlers.items():
                try:
                    _signal.signal(s, hd)
                except (ValueError, TypeError):
                    pass
            self._prev_handlers = None

    def _on_term_signal(self, signum, frame):
        def _drain():
            # bundle on the helper thread, never inside the handler:
            # the interrupted frame may hold locks the bundle's
            # registry collect() needs (engine.py has the same shape)
            fr = _fr_active()
            if fr is not None:
                fr.trigger("signal.sigterm", fleet=self.name,
                           signum=signum)
            self.stop(drain=True)

        threading.Thread(target=_drain,
                         name="mxnet_tpu-fleet-drain",
                         daemon=True).start()

    # ------------------------------------------------------------ forensics
    def _replica_death(self, h: ReplicaHandle, reason: str) -> None:
        """One replica transitioned to DEAD (monitor probe, failing
        submit, or a dropped in-flight attempt): count it, and give the
        flight recorder its trigger — a replica death is exactly the
        moment an operator asks what the fleet was doing."""
        self._count("replica_deaths")
        # a corpse must not attract affinity traffic: drop every
        # directory entry pointing at it (a rebuilt successor starts
        # with an empty pool and re-earns residency on fresh traffic)
        self._directory.forget_replica(h.name)
        fr = _fr_active()
        if fr is not None:
            fr.trigger("fleet.replica_death", fleet=self.name,
                       replica=h.name, reason=reason,
                       deaths=h.total_deaths)

    # ------------------------------------------------- disaggregated serving
    def _wire_migration(self, h: ReplicaHandle) -> None:
        """(Re)attach a prefill-role replica's migration egress to the
        router's decode-placement shim.  Called at construction and
        after every rebuild — a fresh engine starts with no target, and
        an unwired prefill replica silently serves colocated, which is
        safe but defeats the disaggregation."""
        if h.role != "prefill":
            return
        h.engine.migrate_to(
            lambda bundle, future: self._place_decode(bundle, future))

    def _decode_order(self, key: Optional[bytes],
                      candidates: List[ReplicaHandle]
                      ) -> List[ReplicaHandle]:
        """Decode-stage placement order: directory affinity (the
        replica already holding this family's pages — a cross-replica
        prefix hit on arrival), then HRW rank, then load.  Saturated
        affinity targets spill to the back exactly like unified
        placement."""
        by_load = sorted(candidates, key=lambda h: (h.load(), h.name))
        if key is None:
            return by_load
        byname = {h.name: h for h in candidates}
        loc = self._directory.locate(key)
        target = byname.get(loc) if loc is not None else None
        if target is not None and \
                not target.saturated(self.spill_queue_depth):
            self._count("directory_hits")
            return [target] + [h for h in by_load if h is not target]
        self._count("directory_misses")
        ranked = self._policy.rank(key, list(byname))
        target = byname[ranked[0]]
        rest = [h for h in by_load if h is not target]
        if target.saturated(self.spill_queue_depth):
            return rest + [target]
        return [target] + rest

    def _place_decode(self, bundle, future) -> None:
        """Place one migrated KV bundle on a decode-capable replica
        (the second stage of disaggregated placement).  Walks the
        decode order, offering the bundle via ``adopt()``; the first
        acceptor owns the request and its residency is published to
        the directory so the family's followers decode on the same
        pool.  Raises typed when nobody accepts — the prefill engine
        catches it and finishes the request itself (colocated
        fallback), so a refusal here degrades, never loses."""
        candidates = [h for h in self._healthy() if h.can_decode()]
        if not candidates:
            self._count("migration_spills")
            raise NoHealthyReplicaError(
                f"fleet {self.name!r}: no healthy decode-capable "
                f"replica to adopt the bundle")
        # the affinity key rides the bundle as submit()'s route_hint —
        # re-deriving it here would self-match the prompt in the radix
        # tracker (it was recorded at the prefill routing stage) and
        # key every family member uniquely
        key = bundle.route_hint
        last: Optional[Exception] = None
        for h in self._decode_order(key, candidates):
            try:
                h.engine.adopt(bundle, future)
            except ServingError as e:
                # typed refusal (out of slots/pages, stopping, injected
                # migrate_in fault): offer the next candidate
                last = e
                continue
            h.routed += 1
            self._count("migrations")
            self._directory.publish(key, h.name)
            return
        self._count("migration_spills")
        raise last if last is not None else NoHealthyReplicaError(
            f"fleet {self.name!r}: every decode-capable replica "
            f"refused the bundle")

    # ----------------------------------------------------------- monitor
    def _monitor_loop(self):
        while not self._mon_stop.wait(self.health_interval):
            for h in self._members:
                try:
                    if h.probe():
                        self._replica_death(h, h.last_error
                                            or "health probe failed")
                    elif h.due_for_readmission() and not self._stopping:  # raceguard: unguarded(one-way stop flag: atomic bool read; the stop path itself serializes under _stop_lock)
                        # abort= closes the stop-vs-rebuild race: a
                        # rebuild still in flight when the fleet stops
                        # discards its replacement engine instead of
                        # resurrecting a replica on a stopped fleet
                        if h.rebuild(abort=lambda: self._stopping):  # raceguard: unguarded(one-way stop flag: atomic bool read; the stop path itself serializes under _stop_lock)
                            # a rebuilt prefill-role engine starts with
                            # no migration target — re-wire it
                            self._wire_migration(h)
                            self._count("readmissions")
                            fr = _fr_active()
                            if fr is not None:
                                fr.record("fleet.readmission",
                                          fleet=self.name,
                                          replica=h.name)
                    elif h.due_for_unsuspect() and not self._stopping:  # raceguard: unguarded(one-way stop flag: atomic bool read; the stop path itself serializes under _stop_lock)
                        # suspension elapsed: back to traffic with a
                        # fresh latency window — no rebuild, the engine
                        # never stopped (docs/integrity.md)
                        if h.unsuspect():
                            self._count("gray_readmissions")
                            fr = _fr_active()
                            if fr is not None:
                                fr.record("fleet.gray_readmission",
                                          fleet=self.name,
                                          replica=h.name)
                except Exception:
                    continue       # the monitor must outlive any probe
            try:
                self._gray_check()
            except Exception:
                pass               # ...and outlive the detector too

    def _gray_check(self, now: Optional[float] = None) -> None:
        """Gray-failure detector (docs/integrity.md): compare each
        HEALTHY replica's completion-latency EWMA + windowed p99 against
        the median of its PEERS' EWMAs — the candidate is excluded from
        its own median, else its own outlier latency inflates the very
        bar it is judged by (with two replicas the inclusive median
        makes ejection mathematically impossible for any multiplier
        >= 2).  An outlier ``gray_multiplier`` above its peer median
        (with ``gray_min_samples`` of evidence, and at least two
        replicas eligible so a peer exists to disagree with) is
        SUSPECT-ejected.  A replica comfortably under the bar resets
        its consecutive-suspect ladder, mirroring how a healthy probe
        resets the death ladder."""
        if not self.gray_ejection:
            return
        snaps = [(h, h.latency.snapshot()) for h in self._members
                 if h.state == HEALTHY]
        eligible = [(h, s) for h, s in snaps
                    if s["count"] >= self.gray_min_samples]
        if len(eligible) < 2:
            return
        ewmas = [s["ewma"] for _h, s in eligible]
        for i, (h, s) in enumerate(eligible):
            med = _statistics.median(ewmas[:i] + ewmas[i + 1:])
            if med <= 0.0:
                continue
            bar = self.gray_multiplier * med
            if s["ewma"] >= bar and s["p99"] >= bar:
                if h.mark_suspect(
                        f"gray failure: ewma {s['ewma'] * 1e3:.1f}ms / "
                        f"p99 {s['p99'] * 1e3:.1f}ms >= "
                        f"{self.gray_multiplier:g}x peer median "
                        f"{med * 1e3:.1f}ms over {s['count']} samples",
                        now):
                    self._count("gray_ejections")
                    fr = _fr_active()
                    if fr is not None:
                        fr.record("fleet.gray_ejection", fleet=self.name,
                                  replica=h.name,
                                  ewma_ms=round(s["ewma"] * 1e3, 2),
                                  p99_ms=round(s["p99"] * 1e3, 2),
                                  peer_median_ms=round(med * 1e3, 2))
            else:
                h.suspects = 0

    def _observe_completion(self, handle: ReplicaHandle,
                            seconds: float) -> None:
        """Completion path → gray-failure evidence: one served request's
        attempt latency lands in its replica's tracker."""
        handle.observe_latency(seconds)

    # ------------------------------------------------------------ routing
    def _healthy(self) -> List[ReplicaHandle]:
        return [h for h in self._members if h.routable()]

    def _order_candidates(self, payload
                          ) -> Tuple[List[ReplicaHandle],
                                     Optional[bytes]]:
        """Placement order for one NEW request, plus its affinity key
        (``None`` when unkeyed) so the caller can publish where it
        actually landed into the fleet directory.  In a disaggregated
        fleet this is the PREFILL stage: only prefill-capable replicas
        are candidates (decode-role replicas receive work through
        ``adopt()``), ordered by load — prefill is compute-bound, so
        load beats affinity here and the directory steers the DECODE
        stage instead."""
        healthy = self._healthy()
        if self.disaggregated:
            healthy = [h for h in healthy if h.can_prefill()]
        if not healthy:
            self._count("no_healthy")
            raise NoHealthyReplicaError(
                f"fleet {self.name!r}: no healthy "
                f"{'prefill-capable ' if self.disaggregated else ''}"
                f"replica ({ {h.name: h.state for h in self._members} })")
        key, faulted = None, False
        try:
            _inject("fleet.route")
            if self.routing == "affinity" and self.mode == "decode":
                key = self._policy.affinity_key(payload)
        except Exception:
            # contained: the request just loses the routing shortcut
            # and places least-loaded, it never fails
            self._count("route_faults")
            key, faulted = None, True
        if self.routing == "random" and not faulted:
            with self._rng_lock:
                order = list(healthy)
                self._rng.shuffle(order)
            self._count("random_routed")
            return order, None
        by_load = sorted(healthy, key=lambda h: (h.load(), h.name))
        if self.disaggregated:
            # prefill stage: pure load placement; the key still rides
            # back so the decode stage's directory learns the family
            self._count("least_loaded_routed")
            return by_load, key
        if key is None:
            self._count("least_loaded_routed")
            return by_load, None
        # directory affinity beats HRW: the replica that already HOLDS
        # this family's KV (learned from where earlier members landed)
        # wins even when the fleet membership changed since — HRW only
        # decides for families the directory has never seen
        loc = self._directory.locate(key)
        target = self._name_map.get(loc) if loc is not None else None
        if target is not None and target in healthy and \
                not target.saturated(self.spill_queue_depth):
            self._count("directory_hits")
            self._count("affinity_routed")
            return [target] + [h for h in by_load if h is not target], key
        # unknown family, or stale/unusable residency (dead replica,
        # saturated) — fall through to the stateless rank
        self._count("directory_misses")
        ranked = self._policy.rank(key, [h.name for h in healthy])
        target = self._name_map[ranked[0]]
        rest = [h for h in by_load if h is not target]
        if target.saturated(self.spill_queue_depth):
            self._count("affinity_spills")
            return rest + [target], key
        self._count("affinity_routed")
        return [target] + rest, key

    def _submit_once(self, req: _FleetRequest,
                     exclude: Optional[Set[str]] = None
                     ) -> Tuple[ReplicaHandle, object]:
        """Place ``req`` on the best available replica: walk the policy
        order, skipping replicas with an OPEN circuit breaker and
        replicas that shed, and marking replicas whose submit fails
        replica-level as dead.  When every candidate sheds (or sits
        behind an open breaker) the fleet is saturated: coordinated
        brownout is noted and the typed :class:`FleetSaturatedError`
        surfaces.  A :class:`DeadlineInfeasibleError` from one replica
        is retried on less-loaded candidates but — if nobody can make
        the deadline — surfaces AS the deadline error, never laundered
        into a queue-full shed."""
        now = time.monotonic()
        remaining = req.remaining(now)
        if remaining is not None and remaining <= 0:
            raise RequestTimeoutError(
                "request deadline elapsed before it could be placed "
                "on a replica")
        shed = infeasible = None
        breaker_skips = 0
        order, key = self._order_candidates(req.payload)
        for h in order:
            if exclude and h.name in exclude:
                continue
            if not h.breaker.allow(now):
                breaker_skips += 1
                self._count("breaker_skips")
                continue
            try:
                fut = h.engine.submit(req.payload, req.max_new_tokens,
                                      timeout=req.remaining(),
                                      eos_id=req.eos_id,
                                      priority=req.priority,
                                      route_hint=key
                                      if self.disaggregated else None,
                                      **req.sampling)
            except DeadlineInfeasibleError as e:
                # the deadline is the REQUEST's own constraint — a
                # less-loaded candidate may still make it; the breaker
                # is untouched (one impatient client must not open
                # breakers on healthy replicas), but a consumed
                # half-open probe slot is freed so the replica isn't
                # unroutable for a forfeited cooldown
                h.breaker.release_probe()
                self._count("deadline_sheds")
                infeasible = e
                continue
            except QueueFullError as e:
                self._count("sheds")
                h.breaker.record_failure(now)
                shed = e
                continue
            except (EngineCrashedError, EngineStoppedError) as e:
                h.breaker.record_failure(now)
                if isinstance(e, EngineCrashedError) and \
                        h.mark_dead(str(e)):
                    self._replica_death(h, str(e))
                continue
            except InvalidRequestError:
                h.breaker.release_probe()
                raise              # the request's own fault — no failover
            h.breaker.record_success()
            h.routed += 1
            self._count("routed")
            if not self.disaggregated:
                # residency follows placement: this replica is about
                # to prefill (and cache) the family's prefix.  In a
                # disaggregated fleet residency is created by adopt()
                # on the DECODE side — _place_decode publishes there.
                self._directory.publish(key, h.name)
            return h, fut
        if infeasible is not None:
            raise infeasible       # original deadline semantics, always
        if shed is not None or breaker_skips:
            # healthy replicas exist but ALL are saturated (shedding
            # now, or breaker-open from shedding moments ago).  Only a
            # FULL walk is saturation evidence: a hedge/failover probe
            # with replicas excluded never saw the whole fleet, and
            # partial evidence must not force brownout on the healthy
            # replicas it skipped.
            browned = self._note_saturation(now) if not exclude else False
            raise FleetSaturatedError(
                f"fleet {self.name!r}: all healthy replicas saturated "
                f"(breaker-open skips: {breaker_skips}) — back off or "
                "scale up"
                + ("; coordinated brownout engaged" if browned else ""))
        self._count("no_healthy")
        raise NoHealthyReplicaError(
            f"fleet {self.name!r}: no healthy replica accepted the "
            "request")

    def _note_saturation(self, now: float) -> bool:
        """Track all-replicas-shed submits; ``saturation_threshold``
        of them inside ``saturation_window`` seconds force every
        replica's overload controller to its brownout floor — the
        fleet degrades service coherently instead of each replica
        discovering the storm alone.  Returns True iff THIS call
        triggered the coordinated brownout."""
        if not self.saturation_brownout:
            return False
        with self._sat_lock:
            # threshold events must land inside ONE window — a sliding
            # check over the last N event times, not a gap-reset streak
            # (a trickle of one saturated submit every window-minus-ε
            # seconds must never read as a storm)
            self._sat_times.append(now)
            due = (len(self._sat_times) >= self.saturation_threshold
                   and now - self._sat_times[0] <= self.saturation_window
                   and now - self._sat_brownout_at
                   >= self.saturation_window)
            if due:
                self._sat_brownout_at = now
                self._sat_times.clear()
        if due:
            self._count("fleet_brownouts")
            fr = _fr_active()
            if fr is not None:
                fr.record("fleet.brownout", fleet=self.name)
            for h in self._healthy():
                try:
                    h.engine.force_brownout("fleet saturated")
                except Exception:
                    pass
        return due

    def _failover(self, req: _FleetRequest,
                  cause: BaseException) -> Tuple[ReplicaHandle, object]:
        """A replica failed the request mid-flight: resubmit elsewhere
        — within the ORIGINAL deadline and the bounded failover budget
        (neither is ever reset by a failover, so the fleet can never
        double-count a request's time or retries)."""
        if req.remaining() is not None and req.remaining() <= 0:
            raise RequestTimeoutError(
                "request deadline elapsed during replica failover") \
                from cause
        if req.failovers_left <= 0:
            self._count("failover_exhausted")
            raise cause
        # a faulted failover attempt aborts BEFORE the budget check —
        # the containment contract (resilience/faults.py) is that a
        # fleet.failover fault leaves budgets untouched
        try:
            _inject("fleet.failover")
        except BaseException:
            self._count("failover_faults")
            raise cause
        # the fleet-wide token bucket caps ADDED retry load across all
        # requests: when it is dry the original failure surfaces typed
        # — a replica crash during saturation must not fan out into a
        # resubmission herd (docs/overload.md)
        if not self._retry_budget.try_acquire():
            self._count("retry_budget_exhausted")
            raise cause
        req.failovers_left -= 1
        self._count("failovers")
        try:
            return self._submit_once(req)
        except ServingError as e:
            raise e from cause

    # ------------------------------------------------------------- submit
    def submit(self, x, max_new_tokens: Optional[int] = None,
               timeout: Optional[float] = None,
               eos_id: Optional[int] = None,
               priority: Optional[str] = None,
               temperature: float = 0.0, top_k: int = 0,
               top_p: float = 1.0, seed: int = 0) -> FleetFuture:
        """Enqueue one request on the fleet; same contract as
        ``InferenceEngine.submit`` with replica placement on top.
        ``timeout`` is the request's fleet-wide server deadline —
        failover resubmissions inherit the REMAINING time, never a
        fresh window.  ``priority`` (docs/overload.md) rides every
        attempt: a failed-over request keeps its class, and the
        sampling params (``temperature``/``top_k``/``top_p``/``seed``,
        docs/serving.md) ride too — seeded draws fold with absolute
        positions, so a failover or hedge reproduces the SAME stream
        on whichever replica wins."""
        if self._stopping:  # raceguard: unguarded(one-way stop flag: atomic bool read; the stop path itself serializes under _stop_lock)
            raise EngineStoppedError("fleet router is stopped")
        if self.mode == "decode":
            payload = onp.asarray(getattr(x, "asnumpy", lambda: x)(),
                                  dtype="int32")
            if payload.ndim == 2 and payload.shape[0] == 1:
                payload = payload[0]
        else:
            payload = onp.asarray(getattr(x, "asnumpy", lambda: x)())
        deadline = time.monotonic() + timeout if timeout else None
        # sampling params ride EVERY attempt unconditionally: the
        # replica engine owns validating them (a forward-mode engine
        # rejects non-defaults typed), so fleet and bare engine keep
        # one contract instead of the router silently dropping them
        req = _FleetRequest(payload, self.mode, max_new_tokens, eos_id,
                            deadline, self.max_failovers,
                            priority=priority,
                            sampling=dict(temperature=temperature,
                                          top_k=top_k, top_p=top_p,
                                          seed=seed))
        handle, inner = self._submit_once(req)
        return FleetFuture(self, req, handle, inner)

    def infer(self, x, max_new_tokens: Optional[int] = None,
              timeout: Optional[float] = None,
              eos_id: Optional[int] = None,
              priority: Optional[str] = None,
              temperature: float = 0.0, top_k: int = 0,
              top_p: float = 1.0, seed: int = 0):
        """Synchronous ``submit()`` + wait (unbounded client wait — the
        fleet resolves every future with a result or a typed error,
        same as the engine)."""
        if self._monitor is None:
            raise ServingError("router not started — call start() or use "
                               "the context manager")
        return self.submit(x, max_new_tokens, timeout, eos_id,
                           priority, temperature=temperature,
                           top_k=top_k, top_p=top_p,
                           seed=seed).result(None)

    # -------------------------------------------------------------- stats
    def _count(self, key: str, n: int = 1):
        with self._counters_lock:
            self._counters[key] = self._counters.get(key, 0) + n

    def health(self) -> dict:
        reps = {}
        for h in self._members:
            try:
                eh = h.engine.health()
            except Exception as e:
                eh = {"live": False, "error": repr(e)}
            reps[h.name] = {"state": h.state, "deaths": h.total_deaths,
                            "suspects": h.total_suspects,
                            "restarts": h.restarts,
                            "breaker": h.breaker.state, "engine": eh}
        healthy = len(self._healthy())
        return {"name": self.name, "ready": healthy > 0
                and not self._stopping,  # raceguard: unguarded(one-way stop flag: atomic bool read; the stop path itself serializes under _stop_lock)
                "healthy": healthy, "replicas": reps}

    def stats(self) -> dict:
        """Fleet-wide snapshot: router counters, per-replica engine
        stats (CURRENT engines — a rebuilt replica starts fresh), and
        the aggregates a fleet dashboard fronts with (total throughput,
        fleet prefix hit rate)."""
        with self._counters_lock:
            router = dict(self._counters)
        replicas, agg = {}, {"submitted": 0, "completed": 0,
                             "tokens_generated": 0, "prefix_hits": 0,
                             "prefix_misses": 0, "prefix_tokens_saved": 0}
        for h in self._members:
            try:
                s = h.engine.stats()
            except Exception as e:
                replicas[h.name] = {"state": h.state, "error": repr(e)}
                continue
            replicas[h.name] = {"state": h.state, "deaths": h.total_deaths,
                                "suspects": h.total_suspects,
                                "restarts": h.restarts, "routed": h.routed,
                                "latency": h.latency.snapshot(),
                                "stats": s}
            agg["submitted"] += s["requests"]["submitted"]
            agg["completed"] += s["requests"]["completed"]
            agg["tokens_generated"] += s["tokens"]["tokens_generated"]
            for k in ("prefix_hits", "prefix_misses",
                      "prefix_tokens_saved"):
                agg[k] += s["prefix_cache"][k]
        looked = agg["prefix_hits"] + agg["prefix_misses"]
        agg["prefix_hit_rate"] = round(agg["prefix_hits"] / looked, 4) \
            if looked else None
        return {
            "fleet": {"name": self.name, "routing": self.routing,
                      "replicas": len(self._members),
                      "healthy": len(self._healthy()),
                      "spill_queue_depth": self.spill_queue_depth,
                      "max_failovers": self.max_failovers,
                      "tracked_prefixes": len(self._policy),
                      "disaggregated": self.disaggregated,
                      "roles": {h.name: h.role for h in self._members},
                      "directory": self._directory.stats(),
                      "gray": {"ejection": self.gray_ejection,
                               "multiplier": self.gray_multiplier,
                               "min_samples": self.gray_min_samples,
                               "window": self.gray_window},
                      "retry_budget": {
                          "available": round(
                              self._retry_budget.available, 2),
                          "burst": self._retry_budget.burst,
                          "rate": self._retry_budget.rate,
                          "denied": self._retry_budget.denied},
                      "breakers": {h.name: h.breaker.state
                                   for h in self._members}},
            "router": router,
            "aggregate": agg,
            "replicas": replicas,
        }

    # ----------------------------------------------------------- registry
    def _register_collector(self):
        """Publish fleet-level series into the process-wide registry
        (docs/observability.md) next to the replicas' own per-engine
        series.  Weakref-bound: a collected router prunes itself from
        the next scrape."""
        from ..observability.registry import default_registry
        ref = weakref.ref(self)

        def _samples():
            r = ref()
            if r is None:
                raise ReferenceError("FleetRouter collected")
            return r.registry_samples()

        default_registry().register_collector(f"fleet:{self.name}",
                                              _samples)

    def registry_samples(self) -> List[dict]:
        lbl = {"fleet": self.name}
        with self._counters_lock:
            counters = dict(self._counters)
        samples = [
            {"name": f"mxtpu_fleet_{k}_total", "kind": "counter",
             "labels": dict(lbl), "value": v, "help": ""}
            for k, v in sorted(counters.items())]
        healthy = 0
        hits = misses = 0
        for h in self._members:
            up = 1 if h.routable() else 0
            healthy += up
            rlbl = {"fleet": self.name, "replica": h.name}
            samples.append({"name": "mxtpu_fleet_replica_up",
                            "kind": "gauge", "labels": dict(rlbl),
                            "value": up, "help": ""})
            samples.append({"name": "mxtpu_fleet_replica_routed_total",
                            "kind": "counter", "labels": dict(rlbl),
                            "value": h.routed, "help": ""})
            samples.append({"name": "mxtpu_fleet_replica_restarts_total",
                            "kind": "counter", "labels": dict(rlbl),
                            "value": h.restarts, "help": ""})
            samples.append({"name": "mxtpu_fleet_replica_breaker_open",
                            "kind": "gauge", "labels": dict(rlbl),
                            "value": 0 if h.breaker.state == "closed"
                            else 1, "help": ""})
            # gray-failure visibility (docs/integrity.md): the same
            # per-replica latency signal the detector judges by, plus
            # the SUSPECT flag itself
            lat = h.latency.snapshot()
            samples.append({"name":
                            "mxtpu_fleet_replica_latency_ewma_seconds",
                            "kind": "gauge", "labels": dict(rlbl),
                            "value": round(lat["ewma"], 6), "help": ""})
            samples.append({"name":
                            "mxtpu_fleet_replica_latency_p99_seconds",
                            "kind": "gauge", "labels": dict(rlbl),
                            "value": round(lat["p99"], 6), "help": ""})
            samples.append({"name": "mxtpu_fleet_replica_suspect",
                            "kind": "gauge", "labels": dict(rlbl),
                            "value": 1 if h.state == SUSPECT else 0,
                            "help": ""})
            try:
                c = h.engine.metrics.counters
                hits += c["prefix_hits"]
                misses += c["prefix_misses"]
            except Exception:
                pass
        samples.append({"name": "mxtpu_fleet_replicas",
                        "kind": "gauge", "labels": dict(lbl),
                        "value": len(self._members), "help": ""})
        samples.append({"name": "mxtpu_fleet_replicas_healthy",
                        "kind": "gauge", "labels": dict(lbl),
                        "value": healthy, "help": ""})
        samples.append({"name": "mxtpu_fleet_retry_budget_available",
                        "kind": "gauge", "labels": dict(lbl),
                        "value": round(self._retry_budget.available, 2),
                        "help": ""})
        samples.append({"name": "mxtpu_fleet_directory_entries",
                        "kind": "gauge", "labels": dict(lbl),
                        "value": len(self._directory), "help": ""})
        looked = hits + misses
        if looked:
            samples.append({"name": "mxtpu_fleet_prefix_hit_rate",
                            "kind": "gauge", "labels": dict(lbl),
                            "value": round(hits / looked, 4), "help": ""})
        return samples

    def __repr__(self):
        return (f"FleetRouter({self.name!r}, routing={self.routing}, "
                f"replicas={len(self._members)}, "
                f"healthy={len(self._healthy())})")
