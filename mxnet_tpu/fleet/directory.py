"""Fleet-wide prefix/page directory (docs/fleet.md "Disaggregated
serving").

The single-replica prefix cache (docs/serving.md) stops paying at the
replica boundary: a prompt family whose K/V pages live on replica A is
a full prefill on replica B.  The :class:`RoutingPolicy` radix tracker
already *keys* families consistently; this directory closes the loop
by remembering **where each family's KV currently resides** — a
bounded, lock-guarded map ``affinity key → (replica name, residency
tick)``.

Placement consults it first: a locate hit steers the request (or the
migrated decode half, in a disaggregated fleet) to the replica whose
pool actually holds the family's pages, ahead of the stateless HRW
rank.  Publishes happen wherever KV residency is CREATED — a routed
admission on a unified fleet, a successful ``adopt()`` on a
decode-role replica — so the directory tracks reality, not intent.

The directory is advisory, never authoritative: an entry can go stale
(the replica evicted the family under pool pressure, died, or was
rebuilt empty).  A stale hit degrades to exactly what no directory
would have done — a prefix miss on an otherwise fine replica — so
correctness never depends on it.  Replica death simply drops every
entry pointing at the corpse (:meth:`forget_replica`); rebuilt
replicas re-earn entries through fresh traffic.

Capacity is LRU-bounded like the tracker: an evicted family re-keys
from scratch, indistinguishable from a cold one.
"""
from __future__ import annotations

import collections
from typing import Dict, Optional

from ..analysis.lockwitness import named_lock as _named_lock

__all__ = ["FleetDirectory"]


class FleetDirectory:
    """Bounded LRU map: affinity key → replica residency."""

    def __init__(self, entries: int = 512):
        self.entries = max(1, int(entries))
        # OrderedDict as LRU: move_to_end on touch, popitem(last=False)
        # to evict the coldest family
        self._map: "collections.OrderedDict[bytes, str]" = \
            collections.OrderedDict()
        self._tick = 0               # publishes seen (residency age)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self._lock = _named_lock("fleet.directory",
                                 "prefix-key -> replica residency map")

    def publish(self, key: Optional[bytes], replica: str) -> None:
        """Record that ``replica`` now holds ``key``'s KV (admission or
        adoption just landed there).  ``key=None`` (prompt too short to
        key) is a no-op.  Last writer wins — residency follows the most
        recent placement, which is where the freshest pages are."""
        if key is None:
            return
        with self._lock:
            self._tick += 1
            self._map[key] = replica
            self._map.move_to_end(key)
            while len(self._map) > self.entries:
                self._map.popitem(last=False)
                self.evictions += 1

    def locate(self, key: Optional[bytes]) -> Optional[str]:
        """Where does ``key``'s KV live?  Counts a hit/miss and
        LRU-touches the entry.  ``None`` for unkeyed prompts and
        unknown families."""
        if key is None:
            return None
        with self._lock:
            name = self._map.get(key)
            if name is None:
                self.misses += 1
                return None
            self.hits += 1
            self._map.move_to_end(key)
            return name

    def forget_replica(self, replica: str) -> int:
        """Drop every entry pointing at ``replica`` (death, rebuild,
        drain) — a corpse must not attract affinity traffic.  Returns
        the number of entries dropped."""
        with self._lock:
            dead = [k for k, v in self._map.items() if v == replica]
            for k in dead:
                del self._map[k]
            return len(dead)

    def reset(self) -> None:
        with self._lock:
            self._map.clear()
            self.hits = self.misses = self.evictions = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._map)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            total = self.hits + self.misses
            return {"entries": len(self._map),
                    "capacity": self.entries,
                    "hits": self.hits,
                    "misses": self.misses,
                    "evictions": self.evictions,
                    "hit_rate": round(self.hits / total, 4)
                    if total else None}
