"""Routing policy for the fleet router: prefix affinity + rendezvous
hashing + least-loaded fallback.

The point of affinity routing is to MULTIPLY the single-engine prefix
cache (docs/serving.md) across replicas: requests that share a system
prompt should land on the replica that already holds that prompt's K/V
in its prefix pool, instead of every replica paying the full prefill
once per prompt family.  Three pieces:

- **Affinity key** (:meth:`RoutingPolicy.affinity_key`): the router
  keeps its own host-side radix tree over routed prompts — the SAME
  :class:`~mxnet_tpu.serving.prefix_cache.PrefixCache` structure the
  engine uses, so the notion of "prefix" is identical on both sides —
  and keys each request by the longest prefix it shares with earlier
  traffic.  The matched length is CAPPED at ``affinity_window`` tokens:
  a prompt family's FIRST request (no match yet, keyed by its head) and
  every later one (full shared-prefix match, capped back to the head)
  then agree on one key, so the whole family converges on one replica
  instead of the opener landing elsewhere.  Families whose shared
  prefix is shorter than the window still key at the true sharing
  boundary — that is what the radix walk buys over a fixed-width hash.

- **Rendezvous (HRW) hashing** (:func:`rendezvous_rank`): each
  (key, replica) pair gets a deterministic score; the request prefers
  replicas in descending score order.  Adding or removing one replica
  remaps only ~1/N of the keyspace — every key whose winner survives
  keeps its winner — which is exactly the property a prefix-cache-
  affine router needs across restarts and drains (consistent-hash
  rings buy the same property with more machinery).

- **Least-loaded fallback**: when a request has no usable prefix (short
  prompt, forward mode) or its affinity target is saturated, replicas
  are ordered by instantaneous load — queue depth plus active slots,
  read from the engine's own gauges — so spill traffic spreads instead
  of piling behind the hot replica.

The tree is caller-thread shared state (``submit`` runs on arbitrary
threads), so unlike the engine-internal ``PrefixCache`` uses, every
tree op here is lock-guarded.
"""
from __future__ import annotations

import hashlib
import threading
from typing import List, Optional, Sequence

from ..analysis.lockwitness import named_lock as _named_lock
from ..serving.errors import ServingError
from ..serving.prefix_cache import PrefixCache

__all__ = ["RoutingPolicy", "rendezvous_rank", "rendezvous_hash"]


def _score(key: bytes, name: str) -> int:
    """Deterministic 64-bit HRW score for (key, replica).  blake2b, not
    ``hash()``: Python string hashing is salted per process, and a
    router restarted on another host must rank replicas identically or
    every cached prefix goes cold on failover."""
    h = hashlib.blake2b(digest_size=8)
    h.update(key)
    h.update(b"\x00")
    h.update(name.encode("utf-8"))
    return int.from_bytes(h.digest(), "big")


def rendezvous_rank(key: bytes, names: Sequence[str]) -> List[str]:
    """Replica names in descending highest-random-weight order for
    ``key``.  Ties (only possible for duplicate names) break on the
    name itself so the order is total and deterministic."""
    return sorted(names, key=lambda n: (_score(key, n), n), reverse=True)


def rendezvous_hash(key: bytes, names: Sequence[str]) -> str:
    """The HRW winner for ``key`` among ``names``."""
    if not names:
        raise ServingError("rendezvous_hash needs at least one name")
    return rendezvous_rank(key, names)[0]


class RoutingPolicy:
    """Affinity-key computation over a bounded radix tracker.

    Parameters
    ----------
    min_tokens : shortest prefix worth affinity-routing on — mirrors the
        engine's ``prefix_min_tokens`` (a shorter match would not be
        cached replica-side either).
    affinity_window : cap on the affinity key length in tokens.  The cap
        is what makes a prompt family's first request and its followers
        (whose radix matches differ: nothing vs everything) key
        identically; it also bounds hashing cost per route.
    tracker_entries : radix-tracker capacity (LRU beyond it) — bounds
        host memory for long-running routers; an evicted family simply
        re-keys from its head, same as a fresh one.
    """

    def __init__(self, min_tokens: int = 4, affinity_window: int = 32,
                 tracker_entries: int = 512):
        self.min_tokens = max(1, int(min_tokens))
        self.affinity_window = max(self.min_tokens, int(affinity_window))
        # row_base=0: the tracker never touches device rows, the pool
        # indices are just LRU tickets bounding the tree
        self._tree = PrefixCache(int(tracker_entries), row_base=0,
                                 min_tokens=self.min_tokens)
        self._lock = _named_lock("fleet.policy.tracker",
                                 "router-side prefix radix tracker")

    def affinity_key(self, tokens) -> Optional[bytes]:
        """The affinity key for a prompt, or ``None`` when it is too
        short to bother.  Looks up the longest shared prefix with
        earlier routed traffic, caps it at the window, and records the
        prompt for later arrivals."""
        n = len(tokens)
        if n < self.min_tokens:
            return None
        with self._lock:
            hit = self._tree.lookup(tokens)
            match = hit[0] if hit is not None else 0
            # record AFTER lookup: a prompt must not match itself, or
            # every request would key at its own full length and no two
            # family members would ever agree
            self._tree.insert(tokens)
        if match >= self.min_tokens:
            key_len = min(match, self.affinity_window)
        else:
            key_len = min(n, self.affinity_window)
        return self._hash_head(tokens, key_len)

    def peek_key(self, tokens) -> Optional[bytes]:
        """The affinity key ``tokens`` WOULD get — without recording
        the prompt in the tracker.  The scale-down seeding path uses
        this to key exported prefix entries: the entry's tokens were
        already routed once (recording them again would be a no-op at
        best and, for a fresh tracker, would self-match later traffic
        at full length), so the peek computes the same key the family's
        followers carry while leaving the tracker untouched."""
        n = len(tokens)
        if n < self.min_tokens:
            return None
        with self._lock:
            hit = self._tree.lookup(tokens)
        match = hit[0] if hit is not None else 0
        if match >= self.min_tokens:
            key_len = min(match, self.affinity_window)
        else:
            key_len = min(n, self.affinity_window)
        return self._hash_head(tokens, key_len)

    @staticmethod
    def _hash_head(tokens, key_len: int) -> bytes:
        head = [int(t) for t in tokens[:key_len]]
        h = hashlib.blake2b(digest_size=16)
        h.update(",".join(map(str, head)).encode("ascii"))
        return h.digest()

    def rank(self, key: bytes, names: Sequence[str]) -> List[str]:
        return rendezvous_rank(key, names)

    def reset(self):
        with self._lock:
            self._tree.reset()

    def __len__(self):
        with self._lock:
            return len(self._tree)
