"""One replica of the fleet: an :class:`InferenceEngine` plus the
health/lifecycle state the router places traffic by.

A replica is always in exactly one state:

- ``HEALTHY`` — routable.  The monitor polls ``engine.health()``; the
  first not-live probe (scheduler died, watchdog condemned, crashed)
  moves it to ``DEAD``.
- ``SUSPECT`` — not routable, but alive: the gray-failure state
  (docs/integrity.md).  The router feeds every completion's latency
  into the handle's :class:`~mxnet_tpu.resilience.integrity.LatencyTracker`;
  a replica whose EWMA *and* windowed p99 sit a configurable multiple
  above its peers' median — slow enough to hurt, healthy enough to keep
  passing ``health()`` — is ejected here.  Unlike ``DEAD`` the engine
  keeps running and FINISHES its in-flight work; new placement skips it
  exactly like a dead replica (so its HRW keyspace remaps ~1/N onto
  the healthy rest).  Re-admission rides the same probation/backoff
  ladder as deaths but WITHOUT a rebuild: when the window elapses the
  latency window is reset and the replica returns to ``HEALTHY`` — its
  warm caches intact, so re-admission costs zero compiles.  A SUSPECT
  that then fails ``health()`` goes ``DEAD`` normally.
- ``DEAD`` — not routable; sitting out a probation window.  The window
  starts at ``probation`` seconds and doubles per consecutive death
  (capped at ``probation_max``): a replica that crashes right back
  after every rebuild backs off instead of flapping traffic onto a
  poisoned host.  When the window elapses and the fleet has an engine
  ``factory``, the monitor REBUILDS the replica — a condemned engine
  can never be restarted (docs/resilience.md), re-admission is a fresh
  engine under the same replica name — optionally re-running
  ``warmup()`` so the newcomer never compiles on traffic.
- ``DRAINING`` — not routable; ``engine.stop(drain=True)`` in progress.
  Queued and in-flight requests on the replica finish; new traffic is
  steered away.  This is the rolling-restart building block.
- ``STOPPED`` — drained (or force-stopped); waiting for ``restart()``
  or fleet shutdown.

State transitions are guarded by the handle's lock; the engine
reference itself is swapped atomically on rebuild, so routing threads
reading ``handle.engine`` mid-readmission see either the corpse (whose
``submit`` raises typed — the router just tries the next candidate) or
the replacement, never a torn handle.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ..analysis.lockwitness import named_lock as _named_lock
from ..resilience.integrity import LatencyTracker
from ..serving.overload import CircuitBreaker

__all__ = ["ReplicaHandle", "HEALTHY", "DEAD", "DRAINING", "STOPPED",
           "SUSPECT"]

HEALTHY = "healthy"
DEAD = "dead"
DRAINING = "draining"
STOPPED = "stopped"
SUSPECT = "suspect"


class ReplicaHandle:
    def __init__(self, name: str, engine, *,
                 factory: Optional[Callable] = None,
                 probation: float = 0.25,
                 probation_backoff: float = 2.0,
                 probation_max: float = 30.0,
                 restart_warmup: bool = True,
                 breaker: Optional[CircuitBreaker] = None,
                 latency_window: int = 64,
                 latency_alpha: float = 0.25):
        self.name = name
        self.engine = engine
        self.factory = factory
        self.probation = float(probation)
        self.probation_backoff = float(probation_backoff)
        self.probation_max = float(probation_max)
        self.restart_warmup = bool(restart_warmup)
        # retry-storm protection (docs/overload.md): consecutive sheds
        # / replica-level submit failures open the breaker and the
        # router stops offering this replica traffic for a cooldown —
        # the breaker OUTLIVES engine rebuilds (it gates the replica
        # slot, not one engine incarnation)
        self.breaker = breaker if breaker is not None else CircuitBreaker()
        self.state = HEALTHY
        self.deaths = 0              # consecutive (resets on healthy probe)
        self.total_deaths = 0
        self.restarts = 0
        self.routed = 0              # requests placed here (router-counted)
        self.probation_until: Optional[float] = None
        self.last_error: Optional[str] = None
        # gray-failure defense (docs/integrity.md): the router feeds
        # per-completion latencies here; the monitor compares this
        # window against its peers' median and SUSPECT-ejects outliers
        self.latency = LatencyTracker(window=latency_window,
                                      alpha=latency_alpha)
        self.suspects = 0            # consecutive gray ejections (ladder)
        self.total_suspects = 0
        self.suspect_until: Optional[float] = None
        # set by the router's MANUAL drain()/rolling_restart() path and
        # cleared on restart()/replace(): the autoscaler must never
        # read a deliberately-draining replica as shrink headroom or
        # its (expectedly rising) queue as scale-up evidence
        self.manual_drain = False
        self._lock = _named_lock("fleet.replica",
                                 "replica lifecycle state")

    # ---------------------------------------------------------------- state
    @property
    def role(self) -> str:
        """Disaggregated-serving role (docs/fleet.md "Disaggregated
        serving") read off the live engine — a rebuild (same factory,
        same config) keeps it without the handle storing a copy that
        could drift from the engine's truth."""
        return getattr(self.engine, "role", "unified")  # raceguard: unguarded(engine ref is swapped atomically on rebuild; the factory rebuilds the same role)

    def can_prefill(self) -> bool:
        """May NEW requests be placed here?  Prefill-role and unified
        replicas take fresh traffic; decode-role replicas only receive
        work through adopt()."""
        return self.role in ("prefill", "unified")

    def can_decode(self) -> bool:
        """May migrated bundles be adopted here?"""
        return self.role in ("decode", "unified")

    def routable(self) -> bool:
        return self.state == HEALTHY  # raceguard: unguarded(placement hot path: atomic str read; a stale verdict is re-validated by the typed submit failure path)

    def load(self) -> int:
        """Instantaneous placement load: admission-queue depth plus
        leased KV slots — the same numbers the engine exports as the
        ``mxtpu_serving_queue_depth`` / ``mxtpu_serving_active_slots``
        registry gauges, read straight off the engine so routing never
        pays a full registry collect()."""
        eng = self.engine  # raceguard: unguarded(engine ref is swapped atomically on rebuild; a corpse read here fails typed and reroutes)
        try:
            q = len(eng._batcher)
            a = eng._alloc.active_count if eng._alloc is not None else 0
            return q + a
        except Exception:
            return 1 << 30           # unreadable replica sorts last

    def queue_depth(self) -> int:
        try:
            return len(self.engine._batcher)  # raceguard: unguarded(engine ref is swapped atomically on rebuild; a corpse read sorts the replica last)
        except Exception:
            return 1 << 30

    def saturated(self, spill_depth: int) -> bool:
        """The affinity-spill test: is this replica's queue deep enough
        that waiting behind it costs more than a prefix miss elsewhere?
        """
        return self.queue_depth() >= spill_depth

    # --------------------------------------------------------------- deaths
    def mark_dead(self, reason: str, now: Optional[float] = None) -> bool:
        """HEALTHY/SUSPECT → DEAD with a fresh probation window; returns
        whether this call made the transition (the monitor and a failing
        submit path may race to report the same corpse).  A SUSPECT that
        actually dies goes DEAD normally — gray ejection never shields a
        real corpse from the rebuild path."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if self.state not in (HEALTHY, SUSPECT):
                return False
            self.state = DEAD
            self.deaths += 1
            self.total_deaths += 1
            self.last_error = reason
            self.suspect_until = None
            window = min(self.probation_max, self.probation *
                         self.probation_backoff ** (self.deaths - 1))
            self.probation_until = now + window
            return True

    def probe(self, now: Optional[float] = None) -> bool:
        """One monitor tick: returns True iff this probe transitioned
        the replica to DEAD.  A healthy probe resets the consecutive-
        death streak (the backoff ladder restarts).  SUSPECT replicas
        are probed too — slow is survivable, dead is not."""
        if self.state not in (HEALTHY, SUSPECT):  # raceguard: unguarded(monitor fast path: atomic str read; the transition re-checks under the lock in mark_dead)
            return False
        try:
            h = self.engine.health()  # raceguard: unguarded(engine ref is swapped atomically on rebuild; probing a corpse reports dead, which is correct)
            live = bool(h["live"])
            reason = h.get("crashed") or "scheduler not live"
        except Exception as e:            # a broken probe IS a dead replica
            live, reason = False, f"health() raised: {e!r}"
        if live:
            with self._lock:
                # the reset must not race a failing submit path's
                # locked mark_dead increment — a lost increment would
                # shorten the probation backoff ladder
                if self.state == HEALTHY:
                    self.deaths = 0
            return False
        return self.mark_dead(str(reason), now)

    # ----------------------------------------------------- gray failure
    def observe_latency(self, seconds: float) -> None:
        """One completed request's latency (router completion path)."""
        self.latency.observe(seconds)

    def mark_suspect(self, reason: str,
                     now: Optional[float] = None) -> bool:
        """HEALTHY → SUSPECT: stop offering this replica traffic but let
        it finish what it holds.  The suspension window rides the same
        probation/backoff ladder as deaths, keyed on CONSECUTIVE gray
        ejections, so a replica that is still slow on every re-admission
        backs off instead of flapping its keyspace."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if self.state != HEALTHY:
                return False
            self.state = SUSPECT
            self.suspects += 1
            self.total_suspects += 1
            self.last_error = reason
            window = min(self.probation_max, self.probation *
                         self.probation_backoff ** (self.suspects - 1))
            self.suspect_until = now + window
            return True

    def due_for_unsuspect(self, now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else now
        with self._lock:
            # state and suspect_until move together under the lock;
            # reading them apart could see SUSPECT with a window that
            # another transition already cleared
            return (self.state == SUSPECT
                    and self.suspect_until is not None
                    and now >= self.suspect_until)

    def unsuspect(self) -> bool:
        """Suspension elapsed: return to HEALTHY with a RESET latency
        window — the replica is judged on fresh samples, not the storm
        that ejected it.  No rebuild, no re-warm: the engine never
        stopped, so its compiled programs and prefix cache are still
        warm and re-admission costs zero compiles on traffic."""
        with self._lock:
            if self.state != SUSPECT:
                return False
            self.state = HEALTHY
            self.suspect_until = None
        self.latency.reset()
        return True

    def due_for_readmission(self, now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else now
        with self._lock:
            # state and probation_until move together under the lock
            return (self.state == DEAD and self.factory is not None
                    and self.probation_until is not None
                    and now >= self.probation_until)

    def rebuild(self, abort: Optional[Callable[[], bool]] = None) -> bool:
        """Probation elapsed: build a fresh engine under this replica's
        name, start it (and re-warm unless ``restart_warmup=False`` —
        a re-admitted replica should not pay compiles on live traffic),
        and go HEALTHY.  A failed rebuild counts as another death and
        extends the backoff window.

        ``abort`` is polled around the (slow: warmup compiles) build:
        when it turns true — the fleet started shutting down mid-
        rebuild — the replacement engine is stopped instead of
        committed, so a stopped fleet can never resurrect a running
        replica."""
        if self.factory is None:
            return False
        # retire the corpse FIRST: a condemned/stopped engine releases
        # its claimed name, so the replacement reclaims the PLAIN name
        # and this replica's metric series keep their labels across
        # restarts instead of drifting to "<name>-2"
        try:
            self.engine.stop(drain=False, timeout=1.0)  # raceguard: unguarded(rebuild runs on the monitor thread, the only engine-ref writer, so its own read cannot race)
        except Exception:
            pass
        try:
            eng = self.factory(self.name)
            if eng._thread is None:
                eng.start()
            if self.restart_warmup:
                eng.warmup()
        except Exception as e:
            with self._lock:
                self.deaths += 1
                self.total_deaths += 1
                self.last_error = f"rebuild failed: {e!r}"
                window = min(self.probation_max, self.probation *
                             self.probation_backoff ** (self.deaths - 1))
                self.probation_until = time.monotonic() + window
            return False
        if abort is not None and abort():
            self._discard(eng)
            return False
        with self._lock:
            self.engine = eng
            self.state = HEALTHY
            self.restarts += 1
            self.probation_until = None
            self.suspect_until = None
        self.latency.reset()       # fresh engine, fresh evidence
        # a rebuilt replica starts with a CLOSED breaker: its fresh,
        # empty queue owes nothing to the corpse's shed streak
        self.breaker.record_success()
        if abort is not None and abort():
            # shutdown landed between the check above and the commit:
            # undo — the fleet's stop sweep may already have passed this
            # handle, so it must not stay HEALTHY with a live engine
            with self._lock:
                self.state = STOPPED
            self._discard(eng)
            return False
        return True

    def _discard(self, eng) -> None:
        try:
            eng.stop(drain=False, timeout=1.0)
        except Exception:
            pass

    def __repr__(self):
        return (f"ReplicaHandle({self.name!r}, "
                f"state={self.state}, "  # raceguard: unguarded(repr diagnostic: atomic reads, momentary staleness is harmless)
                f"deaths={self.total_deaths}, "  # raceguard: unguarded(repr diagnostic: atomic reads, momentary staleness is harmless)
                f"suspects={self.total_suspects}, "  # raceguard: unguarded(repr diagnostic: atomic reads, momentary staleness is harmless)
                f"restarts={self.restarts})")  # raceguard: unguarded(repr diagnostic: atomic reads, momentary staleness is harmless)
