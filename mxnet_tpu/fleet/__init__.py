"""``mxnet_tpu.fleet`` — the multi-replica serving tier.

"Millions of users" means N engines, not one (ROADMAP item 2): a
:class:`FleetRouter` fronts N :class:`~mxnet_tpu.serving.
InferenceEngine` replicas behind the single engine's ``infer`` /
``submit`` / ``stats`` / ``stop`` surface, adding prefix-affinity
placement (rendezvous-hash the shared prompt prefix so a prompt
family's requests land on the replica that already caches it —
multiplying the single-engine prefix-cache TTFT win across the fleet),
health-gated load balancing with probation/backoff re-admission,
gray-failure ejection (a replica that answers ``health()`` but serves
far slower than its peers' median goes ``SUSPECT`` — unroutable but
alive, re-admitted without a rebuild; docs/integrity.md), bounded
failover of crash-failed requests within the original deadline, and
rolling drain/restart for zero-downtime upgrades.  See docs/fleet.md.

Quick start::

    def factory(name):
        return InferenceEngine(net, num_slots=8, prefix_pool_rows=4,
                               name=name)

    with FleetRouter(factory=factory, num_replicas=3) as fleet:
        fleet.warmup()
        futs = [fleet.submit(p, max_new_tokens=32) for p in prompts]
        outs = [f.result() for f in futs]
        print(fleet.stats()["aggregate"]["prefix_hit_rate"])
"""
from ..serving.errors import FleetSaturatedError, NoHealthyReplicaError
from ..serving.overload import CircuitBreaker, RetryBudget
from .autoscaler import FleetAutoscaler
from .directory import FleetDirectory
from .policy import RoutingPolicy, rendezvous_hash, rendezvous_rank
from .replica import (DEAD, DRAINING, HEALTHY, STOPPED, SUSPECT,
                      ReplicaHandle)
from .router import FleetFuture, FleetRouter

__all__ = [
    "FleetRouter", "FleetFuture", "ReplicaHandle", "RoutingPolicy",
    "FleetDirectory", "FleetAutoscaler",
    "rendezvous_hash", "rendezvous_rank",
    "NoHealthyReplicaError", "FleetSaturatedError",
    "RetryBudget", "CircuitBreaker",
    "HEALTHY", "DEAD", "DRAINING", "STOPPED", "SUSPECT",
]
