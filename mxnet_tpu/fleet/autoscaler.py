"""SLO-driven elastic fleet control: the autoscaler policy thread.

The :class:`FleetAutoscaler` closes the loop between the observability
tier and fleet membership (ROADMAP item 3, docs/fleet.md "Elastic
fleet").  Every ``interval`` it reads three aggregate signals off the
HEALTHY replicas:

- **SLO burn rate** — per-replica :class:`~mxnet_tpu.observability.
  slo.SLOTracker` instances (``register=False``: policy-private, not
  scrape-published), reduced to the fleet max.  Burn ≥ 1 means the
  error budget is being spent faster than the window earns it.
- **Error-budget remaining** — the fleet min; a negative value means
  some replica has already blown its budget.
- **Queue pressure / slot utilisation** — the same queue-depth and
  active-slot gauges routing reads, reduced to fleet max (pressure)
  and mean (utilisation).

and turns them into at most one membership action per tick through the
router's :meth:`~mxnet_tpu.fleet.router.FleetRouter.scale_up` /
:meth:`~mxnet_tpu.fleet.router.FleetRouter.scale_down` — the existing
factory rebuild + re-warm path, so a newcomer never compiles on live
traffic and HRW remaps only ~1/N of the keyspace.

**Hysteresis and cooldown** keep oscillating load from thrashing
rebuilds: evidence must persist for ``up_cycles`` (resp.
``down_cycles``) consecutive ticks before an action fires, and each
action arms a cooldown (``up_cooldown`` / ``down_cooldown``) during
which no further action of either direction fires.  Scale-down demands
strictly quieter evidence than scale-up stops at — the dead band
between ``burn_down``/``queue_low`` and ``burn_up``/``queue_high`` is
where a steady fleet lives.

**Fleet-coordinated overload**: with ``coordinate=True`` the
autoscaler also drives every replica's brownout factor cap and
deadline-admission safety from the AGGREGATE pressure fraction, via
:meth:`~mxnet_tpu.serving.InferenceEngine.coordinate_overload`.  One
hot replica (pressure fraction below ½) never drags the fleet into
brownout while its siblings idle; majority pressure throttles the cap
multiplicatively for everyone and stretches admission estimates, and
calm ticks recover it additively — the same AIMD shape as the local
controller.

Every scaling decision is recorded as a flight-recorder lifecycle
event (``fleet.scale_up`` / ``fleet.scale_down``, emitted by the
router) carrying the signal values that justified it, so a forensics
bundle answers "why did the fleet grow at t=412?" without replaying
logs.

A replica in a DELIBERATE drain (manual ``drain()`` /
``rolling_restart()``) vetoes the whole tick: the shrinking fleet and
the victim's rising queue are expected during an upgrade, not evidence
of load — counting them would scale up into a restart and shrink right
after it.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ..analysis.lockwitness import named_lock as _named_lock
from ..serving.errors import ServingError

__all__ = ["FleetAutoscaler"]


class FleetAutoscaler:
    """Grow/shrink a :class:`~mxnet_tpu.fleet.FleetRouter` from SLO and
    saturation signals.

    Parameters
    ----------
    router : the fleet to govern; must have been built with a
        ``factory`` (scale-up constructs replicas through it).
    slo : optional :class:`~mxnet_tpu.observability.slo.SLO`; when
        given, a private tracker per replica engine feeds burn-rate and
        budget-remaining into the decision.  Without it the policy runs
        on queue/utilisation signals alone.
    min_replicas, max_replicas : membership clamp.  The autoscaler
        repairs a fleet below ``min_replicas`` immediately (no
        hysteresis — that is a hole, not an oscillation).
    interval : policy period in seconds (the thread's cadence; tests
        call :meth:`tick` directly for determinism).
    burn_up, queue_high, budget_floor : scale-UP evidence — any one of
        fleet-max burn ≥ ``burn_up``, fleet-max queue ≥ ``queue_high``
        (default: the router's spill depth), or fleet-min budget
        remaining < ``budget_floor``.
    burn_down, queue_low, util_low : scale-DOWN evidence — ALL of
        fleet-max burn ≤ ``burn_down``, fleet-max queue ≤ ``queue_low``
        and mean slot utilisation ≤ ``util_low``.
    up_cycles, down_cycles : consecutive ticks the evidence must
        persist (hysteresis).
    up_cooldown, down_cooldown : seconds after an action during which
        no further action fires.
    coordinate : drive fleet-wide brownout cap + deadline safety from
        aggregate pressure (see module docstring).
    deadline_safety_max : admission-estimate multiplier at full fleet
        pressure; 1.0 disables the stretch.
    """

    def __init__(self, router, *, slo=None,
                 min_replicas: int = 1, max_replicas: int = 4,
                 interval: float = 0.05,
                 burn_up: float = 1.0, burn_down: float = 0.1,
                 budget_floor: float = 0.0,
                 queue_high: Optional[int] = None, queue_low: int = 1,
                 util_low: float = 0.5,
                 up_cycles: int = 2, down_cycles: int = 4,
                 up_cooldown: float = 0.5, down_cooldown: float = 1.0,
                 coordinate: bool = True,
                 deadline_safety_max: float = 2.0):
        if min_replicas < 1:
            raise ServingError("min_replicas must be >= 1 — an empty "
                               "fleet serves nothing")
        if max_replicas < min_replicas:
            raise ServingError(
                f"max_replicas={max_replicas} < min_replicas="
                f"{min_replicas}")
        if router.factory is None:
            raise ServingError(
                "FleetAutoscaler needs a router built with factory= — "
                "scale-up constructs replicas through it")
        if deadline_safety_max < 1.0:
            raise ServingError("deadline_safety_max must be >= 1.0")
        self.router = router
        self.slo = slo
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.interval = float(interval)
        self.burn_up = float(burn_up)
        self.burn_down = float(burn_down)
        self.budget_floor = float(budget_floor)
        self.queue_high = int(queue_high) if queue_high is not None \
            else int(router.spill_queue_depth)
        self.queue_low = int(queue_low)
        self.util_low = float(util_low)
        self.up_cycles = max(1, int(up_cycles))
        self.down_cycles = max(1, int(down_cycles))
        self.up_cooldown = float(up_cooldown)
        self.down_cooldown = float(down_cooldown)
        self.coordinate = bool(coordinate)
        self.deadline_safety_max = float(deadline_safety_max)
        # decision state: streak counters, cooldown stamp, fleet cap.
        # tick() may be driven by the policy thread or directly by
        # tests/benches, so the state is lock-guarded.
        self._lock = _named_lock("fleet.autoscaler",
                                 "autoscaler decision state")
        self._up_streak = 0
        self._down_streak = 0
        self._cooldown_until = 0.0
        self._cap = 1.0
        self._trackers: Dict[int, tuple] = {}   # id(engine) -> (eng, trk)
        self.ticks = 0
        self.actions: List[dict] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ------------------------------------------------------------- signals
    def _burn(self, handles) -> tuple:
        """(fleet-max burn rate, fleet-min budget remaining) over the
        healthy replicas' private SLO trackers; (0.0, None) without an
        SLO.  Trackers are created lazily per ENGINE OBJECT — a rebuilt
        replica gets a fresh tracker with a fresh baseline — and pruned
        when their engine leaves the fleet."""
        if self.slo is None:
            return 0.0, None
        from ..observability.slo import SLOTracker
        live = set()
        burn, budget = 0.0, None
        for h in handles:
            eng = h.engine
            key = id(eng)
            live.add(key)
            pair = self._trackers.get(key)
            if pair is None or pair[0] is not eng:
                try:
                    pair = (eng, SLOTracker(self.slo, eng,
                                            register=False))
                except Exception:
                    continue
                self._trackers[key] = pair
            try:
                records = pair[1].evaluate()
            except Exception:
                continue
            for rec in records:
                burn = max(burn, rec["burn_rate"])
                rem = rec["budget_remaining"]
                budget = rem if budget is None else min(budget, rem)
        for key in list(self._trackers):
            if key not in live:
                del self._trackers[key]
        return burn, budget

    def _signals(self) -> dict:
        """One consistent-enough reading of the aggregate fleet state.
        Gauges are sampled racily (they are atomic reads off live
        engines); the hysteresis streaks absorb single-tick jitter."""
        handles = self.router._healthy()
        queues, utils = [], []
        for h in handles:
            q = h.queue_depth()
            if q >= (1 << 30):          # unreadable replica: skip, the
                continue                 # health monitor owns that story
            queues.append(q)
            eng = h.engine
            try:
                slots = max(1, eng.num_slots)
                active = eng._alloc.active_count \
                    if eng._alloc is not None else 0
                utils.append(min(1.0, active / slots))
            except Exception:
                pass
        burn, budget = self._burn(handles)
        n = len(handles)
        queue_max = max(queues) if queues else 0
        pressured = sum(1 for q in queues if q >= self.queue_high)
        return {
            "replicas": n,
            "queue_max": queue_max,
            "queue_mean": round(sum(queues) / len(queues), 3)
            if queues else 0.0,
            "util_mean": round(sum(utils) / len(utils), 4)
            if utils else 0.0,
            "burn_rate": round(burn, 4),
            "budget_remaining": budget if budget is None
            else round(budget, 6),
            "pressured_frac": round(pressured / n, 4) if n else 0.0,
        }

    # -------------------------------------------------------- coordination
    def _coordinate(self, sig: dict) -> None:
        """AIMD on the fleet-wide brownout cap, driven by the fraction
        of replicas under queue pressure — NOT by any single replica's
        local panic.  Majority pressure throttles everyone; calm ticks
        recover additively.  Deadline-admission safety stretches with
        the same fraction, so a loaded fleet quotes conservatively
        before it sheds."""
        frac = sig["pressured_frac"]
        if frac >= 0.5:
            self._cap = max(0.0, self._cap * 0.7)   # engine clamps to floor
        elif frac == 0.0 and self._cap < 1.0:
            self._cap = min(1.0, self._cap + 0.1)
        safety = 1.0 + frac * (self.deadline_safety_max - 1.0)
        for h in self.router._healthy():
            try:
                h.engine.coordinate_overload(factor_cap=self._cap,
                                             deadline_safety=safety)
            except Exception:
                continue            # a dying replica is the monitor's job

    # ------------------------------------------------------------ decision
    def tick(self) -> dict:
        """One policy evaluation; at most one membership action.
        Returns the decision record (also appended to ``actions`` when
        an action fired) — benches and tests drive this directly."""
        with self._lock:
            return self._tick_locked()

    def _tick_locked(self) -> dict:
        self.ticks += 1
        r = self.router
        if r._stopping:  # raceguard: unguarded(one-way stop flag: atomic bool read; the stop path itself serializes under _stop_lock)
            return {"action": "hold", "reason": "router stopped"}
        # manual-drain veto (drain-vs-autoscaler race): a deliberate
        # drain makes every signal lie — the fleet looks smaller and
        # the survivors look hotter.  Hold everything, including the
        # streak counters, until the operator's action completes.
        draining = r.draining()
        if draining:
            r._count("scale_vetoes")
            return {"action": "veto", "reason": "manual drain in flight",
                    "draining": draining}
        sig = self._signals()
        if self.coordinate:
            self._coordinate(sig)
        n = sig["replicas"]
        now = time.monotonic()
        # floor repair bypasses hysteresis: below min is a hole in the
        # fleet (deaths beyond the monitor's rebuild lag), not noise
        if 0 < n < self.min_replicas:
            return self._act("up", sig, reason="below min_replicas")
        up_evidence = (
            sig["burn_rate"] >= self.burn_up
            or sig["queue_max"] >= self.queue_high
            or (sig["budget_remaining"] is not None
                and sig["budget_remaining"] < self.budget_floor))
        down_evidence = (
            sig["burn_rate"] <= self.burn_down
            and sig["queue_max"] <= self.queue_low
            and sig["util_mean"] <= self.util_low)
        self._up_streak = self._up_streak + 1 if up_evidence else 0
        self._down_streak = self._down_streak + 1 if down_evidence else 0
        if now < self._cooldown_until:
            return {"action": "hold", "reason": "cooldown", "signals": sig}
        if (up_evidence and self._up_streak >= self.up_cycles
                and n < self.max_replicas):
            return self._act("up", sig, reason="sustained pressure")
        if (down_evidence and self._down_streak >= self.down_cycles
                and n > self.min_replicas):
            return self._act("down", sig, reason="sustained idle")
        return {"action": "hold", "signals": sig}

    def _act(self, direction: str, sig: dict, *, reason: str) -> dict:
        """Fire one membership action through the router's elastic
        path.  The router records the flight-recorder lifecycle event
        with these signals attached; a faulted action (fault sites
        ``fleet.scale_up`` / ``fleet.scale_down``) comes back as
        ``None`` — a counted no-op, retried by later ticks once the
        evidence persists again."""
        now = time.monotonic()
        fr_sig = {f"sig_{k}": v for k, v in sig.items() if v is not None}
        fr_sig["reason"] = reason
        try:
            if direction == "up":
                replica = self.router.scale_up(signals=fr_sig)
                self._cooldown_until = now + self.up_cooldown
            else:
                replica = self.router.scale_down(signals=fr_sig)
                self._cooldown_until = now + self.down_cooldown
        except ServingError as e:
            return {"action": "hold", "reason": f"{direction} refused: "
                    f"{e}", "signals": sig}
        self._up_streak = self._down_streak = 0
        rec = {"action": direction if replica is not None else "faulted",
               "replica": replica, "reason": reason, "signals": sig}
        self.actions.append(rec)
        return rec

    # -------------------------------------------------------------- thread
    def start(self) -> "FleetAutoscaler":
        if self._thread is not None:
            raise ServingError("autoscaler already started")
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="mxnet_tpu-fleet-autoscaler",
            daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval):
            try:
                self.tick()
            except Exception:
                # the policy thread must outlive any single bad read; a
                # persistent failure shows up as a frozen ticks counter
                continue

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            # a tick can be mid scale_up (factory build + warmup), which
            # on a cold compile cache takes far longer than one interval;
            # wait it out so callers observe the fired action's effects
            t.join(timeout=60.0)
            if not t.is_alive():
                self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # --------------------------------------------------------------- stats
    def stats(self) -> dict:
        with self._lock:
            return {
                "ticks": self.ticks,
                "actions": list(self.actions),
                "fleet_cap": round(self._cap, 4),
                "up_streak": self._up_streak,
                "down_streak": self._down_streak,
                "cooldown_remaining": round(max(
                    0.0, self._cooldown_until - time.monotonic()), 3),
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
            }

    def __repr__(self):
        return (f"FleetAutoscaler(replicas=[{self.min_replicas},"
                f"{self.max_replicas}], ticks={self.ticks}, "
                f"actions={len(self.actions)})")
