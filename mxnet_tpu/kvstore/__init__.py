"""KVStore: key-value parameter/gradient store (parity: src/kvstore/* +
python/mxnet/kvstore/, SURVEY.md §2.4).

TPU-first mapping: MXNet's comm backends (CommCPU/CommDevice/NCCL/ps-lite)
all collapse into XLA collectives over the device mesh:

- ``local``/``device``/``nccl`` → in-process aggregation; when values are
  sharded jax.Arrays the reduction IS a psum over the ICI mesh axis
  (performed by XLA inside the jitted step — see mxnet_tpu.parallel).
- ``dist_sync``/``dist_async``/``dist_sync_device`` → multi-host: same
  collective API over the global mesh after ``jax.distributed.initialize``
  (ps-lite's scheduler/server roles are replaced by the JAX coordination
  service; there is no server-side optimizer process — ``update_on_kvstore``
  maps to running the optimizer on the aggregated gradient inside the store).
- The ``KVStoreBase`` plugin registry is preserved (MXNet 2.x
  ``python/mxnet/kvstore/base.py``) so ``kvstore='horovod'``-style plugins
  can register a custom backend.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as onp

from .. import base as _base
from ..ndarray import NDArray
from ..resilience.faults import inject as _inject

__all__ = ["KVStore", "KVStoreBase", "create"]

_registry = _base.registry("kvstore")


class KVStoreBase:
    """Plugin base (parity: python/mxnet/kvstore/base.py)."""

    OPTIMIZER = "optimizer"

    @staticmethod
    def register(klass):
        _registry.register(klass.__name__)(klass)
        return klass

    def broadcast(self, key, value, out):
        raise NotImplementedError

    def pushpull(self, key, value, out=None):
        raise NotImplementedError

    @property
    def type(self):
        return type(self).__name__

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    def is_capable(self, capability):
        return True


class KVStore(KVStoreBase):
    """In-process store covering MXNet types local/device/nccl.

    Values that are sharded jax.Arrays reduce via XLA collectives; replicated
    lists (one NDArray per device) reduce by summation with XLA handling the
    cross-device transfers.
    """

    def __init__(self, kv_type="local"):
        self._type = kv_type
        self._store: Dict = {}
        self._updater = None
        self._optimizer = None
        self._compression: Optional[dict] = None
        self._residuals: Dict = {}   # (key, device_idx) -> error feedback
        # fleet counters (docs/observability.md): push/pull traffic per
        # kvstore type.  Created once here — inc() on the push/pull
        # path is a per-metric lock, not a registry lookup.
        from ..observability.registry import default_registry
        _reg = default_registry()
        self._obs_push = _reg.counter("mxtpu_kvstore_push_total",
                                      help="kvstore push calls",
                                      type=kv_type)
        self._obs_pull = _reg.counter("mxtpu_kvstore_pull_total",
                                      help="kvstore pull calls",
                                      type=kv_type)

    # -- identity ---------------------------------------------------------
    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        try:
            return jax.process_index()
        except RuntimeError:
            return 0

    @property
    def num_workers(self):
        try:
            return jax.process_count()
        except RuntimeError:
            return 1

    # -- core ops ---------------------------------------------------------
    def init(self, key, value):
        keys, values = _normalize(key, value)
        for k, v in zip(keys, values):
            self._store[k] = v[0].copy() if isinstance(v, list) else v.copy()

    def _reduce(self, k, vals: List[NDArray]) -> jax.Array:
        """Aggregate one key's per-device values (parity: Comm::Reduce).

        Values land on the first value's device and reduce in ONE fused
        XLA sum over a stacked buffer (not an O(n) add chain); worker-side
        gradient compression (2-bit with error feedback) applies before
        the reduce when configured, like kvstore_dist's
        gradient_compression.cc."""
        dev = _device_of(vals[0].jax)
        arrs = [vals[0].jax] + [jax.device_put(v.jax, dev)
                                for v in vals[1:]]
        if self._compression and \
                str(self._compression.get("type", "none")) == "2bit":
            thr = float(self._compression.get("threshold", 0.5))
            arrs = [self._compress_2bit(k, i, a, thr)
                    for i, a in enumerate(arrs)]
        if len(arrs) == 1:
            return arrs[0]
        return jnp.sum(jnp.stack(arrs), axis=0)

    def _compress_2bit(self, key, idx, grad, threshold):
        """{-t, 0, +t} quantization with per-(key, device) error feedback
        (parity: src/kvstore/gradient_compression.cc 2-bit scheme)."""
        res = self._residuals.get((key, idx))
        g = grad if res is None else grad + res
        q = jnp.where(g >= threshold, threshold,
                      jnp.where(g <= -threshold, -threshold,
                                jnp.zeros_like(g)))
        self._residuals[(key, idx)] = g - q
        return q

    def push(self, key, value, priority=0):
        _inject("kvstore.push")
        self._obs_push.inc()
        from ..ndarray.sparse import RowSparseNDArray, _RowSparseCot
        keys, values = _normalize(key, value)
        for k, v in zip(keys, values):
            vals = v if isinstance(v, list) else [v]
            if k not in self._store:
                raise _base.MXNetError(f"key {k} not initialized")
            if self._updater is not None and not self._compression and \
                    self._type.startswith("dist_async"):
                # async PS semantics (kvstore_dist async mode): NO merge
                # barrier — each pushed value applies its own optimizer
                # update as it "arrives", so stateful optimizers see a
                # sequence of small updates instead of one merged one
                for x in vals:
                    g = x if isinstance(x, RowSparseNDArray) \
                        else NDArray(x.jax)
                    self._updater(k, g, self._store[k])
                continue
            if self._updater is not None and not self._compression and \
                    all(isinstance(x, RowSparseNDArray) for x in vals):
                # keep row-sparse grads compact into the updater's lazy
                # row-wise path (parity: kvstore_local's sparse push)
                if len(vals) == 1:
                    agg_rs = vals[0]
                else:
                    cot = _RowSparseCot(vals[0]._sp_data,
                                        vals[0]._sp_indices,
                                        vals[0]._sp_shape)
                    for x in vals[1:]:
                        cot = cot + _RowSparseCot(x._sp_data, x._sp_indices,
                                                  x._sp_shape)
                    agg_rs = RowSparseNDArray.from_components(
                        cot.data, cot.indices, cot.shape,
                        ctx=vals[0].context)
                self._updater(k, agg_rs, self._store[k])
                continue
            agg = self._reduce(k, vals)
            if self._updater is not None:
                # update_on_kvstore: run optimizer on aggregated grad
                self._updater(k, NDArray(agg), self._store[k])
            else:
                self._store[k]._rebind(agg)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        _inject("kvstore.pull")
        self._obs_pull.inc()
        keys, outs = _normalize(key, out)
        for k, o in zip(keys, outs):
            targets = o if isinstance(o, list) else [o]
            src = self._store[k]
            for t in targets:
                t._rebind(jax.device_put(src.jax, t.context.jax_device))

    def pushpull(self, key, value, out=None, priority=0):
        """Fused push+pull: ONE reduce per key (upstream
        KVStore::PushPull), store updated per push semantics, aggregate
        broadcast to ``out``."""
        keys, values = _normalize(key, value)
        outs = _normalize(key, out)[1] if out is not None else \
            [None] * len(keys)
        for k, v, o in zip(keys, values, outs):
            vals = v if isinstance(v, list) else [v]
            agg = self._reduce(k, vals)
            if k not in self._store:
                raise _base.MXNetError(f"key {k} not initialized")
            if self._updater is not None:
                self._updater(k, NDArray(agg), self._store[k])
                agg = self._store[k].jax     # pull the updated weight
            else:
                self._store[k]._rebind(agg)
            if o is not None:
                targets = o if isinstance(o, list) else [o]
                for t in targets:
                    t._rebind(jax.device_put(agg, t.context.jax_device))

    def broadcast(self, key, value, out, priority=0):
        self.init(key, value)
        self.pull(key, out=out, priority=priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the requested rows of a key (parity: upstream
        KVStore::PullRowSparse over `src/kvstore/kvstore_local.cc`'s
        unique-key gather).  `out` RowSparseNDArrays receive compact
        (rows, indices) payloads — the full (vocab, dim) value is never
        materialized on the pulling side.  Dense `out` (or no row_ids)
        falls back to a full pull."""
        from ..ndarray.sparse import RowSparseNDArray
        if row_ids is None:
            self.pull(key, out=out, priority=priority)
            return
        keys, outs = _normalize(key, out)
        rows_list = row_ids if isinstance(row_ids, list) else \
            [row_ids] * len(keys)
        for k, o, rids in zip(keys, outs, rows_list):
            targets = o if isinstance(o, list) else [o]
            src = self._store[k]
            ids = rids.asnumpy() if isinstance(rids, NDArray) else \
                onp.asarray(rids)
            uniq = onp.unique(ids.astype("int64").reshape(-1))
            if uniq.size and (uniq[0] < 0 or uniq[-1] >= src.shape[0]):
                raise _base.MXNetError(
                    f"row_sparse_pull row_ids out of range for key {k}: "
                    f"[{uniq[0]}, {uniq[-1]}] vs {src.shape[0]} rows")
            uniq_j = jnp.asarray(uniq, jnp.int32)
            rows = src.jax[uniq_j]
            for t in targets:
                if isinstance(t, RowSparseNDArray):
                    dev = t.context.jax_device
                    t._sp_shape = tuple(src.shape)
                    t._set_components(jax.device_put(rows, dev),
                                      jax.device_put(uniq_j, dev))
                else:
                    t._rebind(jax.device_put(src.jax,
                                             t.context.jax_device))

    # -- optimizer --------------------------------------------------------
    def set_optimizer(self, optimizer):
        from .. import optimizer as opt_mod
        self._optimizer = optimizer
        self._updater = opt_mod.get_updater(optimizer)

    @property
    def updater(self):
        return self._updater

    def set_gradient_compression(self, compression_params):
        """2-bit gradient compression with error feedback (parity:
        src/kvstore/gradient_compression.cc).  On TPU the ICI allreduce
        rarely needs it, but the semantics (worker-side quantization to
        {-t, 0, +t} + residual accumulation) are implemented faithfully
        for the eager push path; {'type': 'none'} disables."""
        params = dict(compression_params or {})
        ctype = str(params.get("type", "none"))
        if ctype not in ("none", "2bit"):
            raise _base.MXNetError(
                f"unsupported gradient compression type {ctype!r} "
                "(supported: 'none', '2bit')")
        self._compression = params if ctype != "none" else None
        self._residuals.clear()

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise _base.MXNetError("kvstore has no optimizer")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise _base.MXNetError("kvstore has no optimizer")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())


def _device_of(arr):
    devs = getattr(arr, "devices", None)
    if devs is not None:
        ds = arr.devices()
        return next(iter(ds))
    return None


def _normalize(key, value):
    if isinstance(key, (list, tuple)):
        return list(key), list(value)
    return [key], [value]


for _t in ("local", "device", "nccl", "tpu", "dist_sync", "dist_async",
           "dist_sync_device", "dist_async_device", "dist"):
    _registry.register(_t)(KVStore)


def create(name="local") -> KVStore:
    """Parity: mx.kv.create('device'|'nccl'|'dist_sync'|...)."""
    cls = _registry.get(name)
    if cls is KVStore:
        return KVStore(name)
    return cls()
