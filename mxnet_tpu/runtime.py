"""Runtime feature detection (parity: python/mxnet/runtime.py +
src/libinfo.cc, SURVEY.md §5.6.3).

Build flags become runtime facts on TPU: features reflect what the JAX
backend actually provides in this process (TPU present, Pallas usable,
distributed initialized, ...), so tests can gate with
``mx.runtime.Features()["TPU"].enabled`` the way MXNet tests gate on CUDA.
"""
from __future__ import annotations

from collections import OrderedDict, namedtuple

__all__ = ["Feature", "Features", "feature_list"]

Feature = namedtuple("Feature", ["name", "enabled"])


def _detect():
    import jax

    feats = OrderedDict()

    def add(name, enabled):
        feats[name] = Feature(name, bool(enabled))

    platforms = set()
    try:
        platforms = {d.platform for d in jax.devices()}
    except Exception:
        pass
    add("TPU", "tpu" in platforms or "axon" in platforms)
    add("CUDA", "gpu" in platforms or "cuda" in platforms)
    add("CPU", True)
    add("CPU_SSE", True)   # XLA:CPU vectorizes; kept for API compat
    add("BLAS_OPEN", True)
    add("F16C", True)
    add("BF16", True)      # native on TPU
    add("INT64_TENSOR_SIZE", False)
    add("SIGNAL_HANDLER", False)
    add("PROFILER", True)  # jax.profiler bridge
    try:
        import jax.experimental.pallas  # noqa: F401
        add("PALLAS", True)
    except ImportError:
        add("PALLAS", False)
    add("DIST_KVSTORE", True)  # jax.distributed collectives
    try:
        from .utils import native
        add("NATIVE_IO", native.available())
    except Exception:
        add("NATIVE_IO", False)
    add("ONEDNN", False)
    add("TENSORRT", False)
    add("OPENCV", False)   # PIL-backed image path instead
    return feats


class Features(OrderedDict):
    """Mapping of feature name → Feature (parity: mx.runtime.Features)."""

    instance = None

    def __new__(cls):
        if cls.instance is None:
            cls.instance = super().__new__(cls)
            OrderedDict.__init__(cls.instance, _detect())
        return cls.instance

    def __init__(self):
        pass

    def __repr__(self):
        return f"[{', '.join(self.keys())}]"

    def is_enabled(self, name):
        return self[name].enabled


def feature_list():
    return list(Features().values())
