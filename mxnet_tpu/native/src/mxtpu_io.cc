// mxtpu_io: native data plane for mxnet_tpu.
//
// TPU-native re-expression of MXNet's C++ IO stack (parity:
// 3rdparty/dmlc-core/include/dmlc/recordio.h framing,
// src/io/iter_image_recordio_2.cc threaded decode pipeline,
// src/io/image_aug_default.cc default augmenter semantics).  The device
// side of MXNet's native code is replaced by XLA; THIS is the host-side
// hot path XLA does not cover: record framing, pread fan-out, libjpeg
// decode, resize/crop/mirror/normalize — all off the GIL on a worker
// pool, returning ready NCHW float batches in deterministic order.
//
// C ABI only (loaded via ctypes; no pybind dependency).

#include <cstddef>
#include <cstdio>

#include <jpeglib.h>

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <csetjmp>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0xced7230au;
constexpr uint32_t kLenMask = (1u << 29) - 1u;

// ------------------------------------------------------------------ writer

struct Writer {
  FILE* f;
};

// ------------------------------------------------------------- jpeg decode

struct JpegErr {
  jpeg_error_mgr pub;
  jmp_buf jb;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  JpegErr* e = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(e->jb, 1);
}

// decode to RGB HWC uint8; returns false on any libjpeg error
bool decode_jpeg(const uint8_t* buf, size_t len, std::vector<uint8_t>* out,
                 int* w, int* h) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(buf),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  *w = cinfo.output_width;
  *h = cinfo.output_height;
  out->resize(static_cast<size_t>(*w) * (*h) * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = out->data() +
                   static_cast<size_t>(cinfo.output_scanline) * (*w) * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

// ------------------------------------------------------- bilinear resize

void resize_bilinear(const uint8_t* src, int sw, int sh,
                     std::vector<uint8_t>* dst, int dw, int dh) {
  dst->resize(static_cast<size_t>(dw) * dh * 3);
  const float xs = sw > 1 ? float(sw - 1) / std::max(dw - 1, 1) : 0.f;
  const float ys = sh > 1 ? float(sh - 1) / std::max(dh - 1, 1) : 0.f;
  for (int y = 0; y < dh; ++y) {
    float fy = y * ys;
    int y0 = static_cast<int>(fy);
    int y1 = std::min(y0 + 1, sh - 1);
    float wy = fy - y0;
    for (int x = 0; x < dw; ++x) {
      float fx = x * xs;
      int x0 = static_cast<int>(fx);
      int x1 = std::min(x0 + 1, sw - 1);
      float wx = fx - x0;
      for (int c = 0; c < 3; ++c) {
        float v00 = src[(static_cast<size_t>(y0) * sw + x0) * 3 + c];
        float v01 = src[(static_cast<size_t>(y0) * sw + x1) * 3 + c];
        float v10 = src[(static_cast<size_t>(y1) * sw + x0) * 3 + c];
        float v11 = src[(static_cast<size_t>(y1) * sw + x1) * 3 + c];
        float v = v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                  v10 * wy * (1 - wx) + v11 * wy * wx;
        (*dst)[(static_cast<size_t>(y) * dw + x) * 3 + c] =
            static_cast<uint8_t>(v + 0.5f);
      }
    }
  }
}

// ------------------------------------------------------------ pipeline

struct Result {
  std::vector<float> data;    // 3*H*W (CHW, normalized)
  std::vector<float> label;   // label_width
  uint8_t ok;
};

struct Task {
  int64_t epoch, seq, rec;
  uint64_t seed;   // captured at schedule time — workers of an abandoned
                   // epoch must never race the live epoch's seed
};

// high bit of a stored length marks a multipart logical record whose
// offset points at the FIRST FRAME HEADER and whose length spans every
// frame (headers included) through the last frame's payload
constexpr uint64_t kMultipartBit = 1ull << 63;

struct Pipe {
  int fd = -1;
  std::vector<uint64_t> offs, lens;   // payload offset/length per record
  int H, W, resize, rand_crop, rand_mirror, label_width, capacity;
  float mean[3], stdv[3];
  uint64_t seed;

  std::deque<Task> tasks;
  int64_t epoch = 0;                  // bumped by schedule(); stale
                                      // results are discarded
  int64_t epoch_len = 0;
  std::map<int64_t, Result> done;
  int64_t next_out = 0;
  bool stop = false;
  std::mutex mu;
  std::condition_variable cv_task, cv_done;
  std::vector<std::thread> workers;

  void worker() {
    for (;;) {
      Task t;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_task.wait(lk, [&] {
          return stop ||
                 (!tasks.empty() &&
                  done.size() < static_cast<size_t>(capacity));
        });
        if (stop) return;
        t = tasks.front();
        tasks.pop_front();
      }
      Result r = process(t.rec, t.seq, t.seed);
      {
        std::lock_guard<std::mutex> lk(mu);
        if (t.epoch == epoch)        // drop results of abandoned epochs
          done.emplace(t.seq, std::move(r));
      }
      cv_done.notify_all();
    }
  }

  // Reassemble a multipart logical record from its raw frame span: parts
  // are rejoined with the magic word re-inserted (dmlc RecordIOReader).
  static bool reassemble(const std::vector<uint8_t>& span,
                         std::vector<uint8_t>* out) {
    out->clear();
    size_t p = 0;
    bool started = false;
    while (p + 8 <= span.size()) {
      uint32_t magic, lrec;
      std::memcpy(&magic, span.data() + p, 4);
      std::memcpy(&lrec, span.data() + p + 4, 4);
      if (magic != kMagic) return false;
      uint32_t cflag = lrec >> 29;
      size_t len = lrec & kLenMask;
      p += 8;
      if (p + len > span.size()) return false;
      if (cflag == 1) {
        started = true;
        out->assign(span.begin() + p, span.begin() + p + len);
      } else if (cflag == 2 || cflag == 3) {
        if (!started) return false;
        const uint8_t* m = reinterpret_cast<const uint8_t*>(&kMagic);
        out->insert(out->end(), m, m + 4);
        out->insert(out->end(), span.begin() + p, span.begin() + p + len);
        if (cflag == 3) return true;
      } else {
        return false;
      }
      p += len + ((4 - (len & 3)) & 3);
    }
    return false;
  }

  Result process(int64_t rec, int64_t seq, uint64_t seed) {
    Result r;
    r.ok = 0;
    r.data.assign(static_cast<size_t>(3) * H * W, 0.f);
    r.label.assign(label_width, 0.f);
    uint64_t rlen = lens[rec] & ~kMultipartBit;
    std::vector<uint8_t> raw(rlen);
    ssize_t got = pread(fd, raw.data(), rlen,
                        static_cast<off_t>(offs[rec]));
    if (got != static_cast<ssize_t>(rlen)) return r;
    if (lens[rec] & kMultipartBit) {
      std::vector<uint8_t> whole;
      if (!reassemble(raw, &whole)) return r;
      raw.swap(whole);
    }
    if (raw.size() < 24) return r;
    // IRHeader: <IfQQ> flag, label, id, id2 (+ flag floats when flag > 0)
    uint32_t flag;
    float lab;
    std::memcpy(&flag, raw.data(), 4);
    std::memcpy(&lab, raw.data() + 4, 4);
    size_t off = 24;
    if (flag > 0) {
      size_t need = static_cast<size_t>(flag) * 4;
      if (raw.size() < off + need) return r;
      for (int i = 0; i < label_width && i < static_cast<int>(flag); ++i)
        std::memcpy(&r.label[i], raw.data() + off + i * 4, 4);
      off += need;
    } else {
      r.label[0] = lab;
    }
    int w0 = 0, h0 = 0;
    std::vector<uint8_t> rgb;
    if (!decode_jpeg(raw.data() + off, raw.size() - off, &rgb, &w0, &h0))
      return r;
    const uint8_t* img = rgb.data();
    std::vector<uint8_t> tmp;
    int cw = w0, ch = h0;
    if (resize > 0) {
      float s = float(resize) / std::min(w0, h0);
      int nw = std::max(1, int(w0 * s + 0.5f));
      int nh = std::max(1, int(h0 * s + 0.5f));
      resize_bilinear(img, cw, ch, &tmp, nw, nh);
      img = tmp.data(); cw = nw; ch = nh;
    }
    std::vector<uint8_t> tmp2;
    if (cw < W || ch < H) {            // upscale to cover the crop
      int nw = std::max(W, cw), nh = std::max(H, ch);
      resize_bilinear(img, cw, ch, &tmp2, nw, nh);
      img = tmp2.data(); cw = nw; ch = nh;
    }
    std::mt19937_64 rng(seed * 0x9e3779b97f4a7c15ull + seq);
    int x0, y0;
    if (rand_crop) {
      x0 = static_cast<int>(rng() % (cw - W + 1));
      y0 = static_cast<int>(rng() % (ch - H + 1));
    } else {
      x0 = (cw - W) / 2; y0 = (ch - H) / 2;
    }
    bool mirror = rand_mirror && (rng() & 1);
    for (int y = 0; y < H; ++y) {
      for (int x = 0; x < W; ++x) {
        int sx = mirror ? (x0 + W - 1 - x) : (x0 + x);
        const uint8_t* px =
            img + (static_cast<size_t>(y0 + y) * cw + sx) * 3;
        for (int c = 0; c < 3; ++c) {
          r.data[(static_cast<size_t>(c) * H + y) * W + x] =
              (float(px[c]) - mean[c]) / stdv[c];
        }
      }
    }
    r.ok = 1;
    return r;
  }
};

}  // namespace

extern "C" {

// ------------------------------------------------------------------ writer

void* mxio_writer_open(const char* path) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  Writer* w = new Writer{f};
  return w;
}

int64_t mxio_writer_tell(void* h) {
  return ftell(static_cast<Writer*>(h)->f);
}

int mxio_writer_write(void* h, const uint8_t* data, uint64_t len) {
  FILE* f = static_cast<Writer*>(h)->f;
  if (len > kLenMask) return -1;   // 29-bit length field; never truncate
  // dmlc multipart splitting: every 4-byte-aligned magic word inside the
  // payload becomes the next part's frame delimiter (cflag 1/2/3), so
  // upstream dmlc readers reassemble bit-for-bit
  const uint8_t* m = reinterpret_cast<const uint8_t*>(&kMagic);
  uint64_t dptr = 0;
  for (uint64_t i = 0; i + 4 <= len; i += 4) {
    if (std::memcmp(data + i, m, 4) == 0) {
      uint32_t lrec = ((dptr == 0 ? 1u : 2u) << 29) |
                      static_cast<uint32_t>(i - dptr);
      uint32_t hdr[2] = {kMagic, lrec};
      if (fwrite(hdr, 4, 2, f) != 2) return -1;
      if (i != dptr && fwrite(data + dptr, 1, i - dptr, f) != i - dptr)
        return -1;
      dptr = i + 4;
    }
  }
  uint32_t lrec = ((dptr != 0 ? 3u : 0u) << 29) |
                  static_cast<uint32_t>(len - dptr);
  uint32_t hdr[2] = {kMagic, lrec};
  if (fwrite(hdr, 4, 2, f) != 2) return -1;
  if (len != dptr && fwrite(data + dptr, 1, len - dptr, f) != len - dptr)
    return -1;
  static const char zeros[4] = {0, 0, 0, 0};
  size_t pad = (4 - (len & 3)) & 3;
  if (pad && fwrite(zeros, 1, pad, f) != pad) return -1;
  return 0;
}

void mxio_writer_close(void* h) {
  Writer* w = static_cast<Writer*>(h);
  fclose(w->f);
  delete w;
}

// ------------------------------------------------- offset table scan

// Scans a RecordIO file; fills malloc'd offset/length arrays of LOGICAL
// records.  Single-frame records store (payload offset, payload length);
// multipart records (cflag 1/2/3 chains) store (first-frame HEADER offset,
// full span length) with the kMultipartBit marker — the pipeline worker
// reassembles them.  Returns record count, -1 on error/malformed chain.
int64_t mxio_scan(const char* path, uint64_t** offs_out,
                  uint64_t** lens_out) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  std::vector<uint64_t> offs, lens;
  uint32_t hdr[2];
  long chain_start = -1;   // header pos of the open multipart chain
  for (;;) {
    long pos = ftell(f);
    if (fread(hdr, 4, 2, f) != 2) break;
    if (hdr[0] != kMagic) { fclose(f); return -1; }
    uint32_t cflag = hdr[1] >> 29;
    uint64_t len = hdr[1] & kLenMask;
    if (cflag == 0) {
      if (chain_start != -1) { fclose(f); return -1; }
      offs.push_back(static_cast<uint64_t>(pos) + 8);
      lens.push_back(len);
    } else if (cflag == 1) {
      if (chain_start != -1) { fclose(f); return -1; }
      chain_start = pos;
    } else {
      if (chain_start == -1) { fclose(f); return -1; }
      if (cflag == 3) {
        offs.push_back(static_cast<uint64_t>(chain_start));
        lens.push_back((static_cast<uint64_t>(pos) + 8 + len -
                        static_cast<uint64_t>(chain_start)) | kMultipartBit);
        chain_start = -1;
      }
    }
    uint64_t skip = len + ((4 - (len & 3)) & 3);
    if (fseek(f, static_cast<long>(skip), SEEK_CUR) != 0) break;
  }
  fclose(f);
  if (chain_start != -1) return -1;   // truncated multipart chain
  int64_t n = static_cast<int64_t>(offs.size());
  *offs_out = static_cast<uint64_t*>(malloc(n * 8));
  *lens_out = static_cast<uint64_t*>(malloc(n * 8));
  std::memcpy(*offs_out, offs.data(), n * 8);
  std::memcpy(*lens_out, lens.data(), n * 8);
  return n;
}

void mxio_free(void* p) { free(p); }

// ------------------------------------------------------------- pipeline

void* mxio_pipe_open(const char* path, const uint64_t* offs,
                     const uint64_t* lens, int64_t n, int threads, int H,
                     int W, int resize, int rand_crop, int rand_mirror,
                     const float* mean, const float* stdv, uint64_t seed,
                     int label_width, int capacity) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  Pipe* p = new Pipe();
  p->fd = fd;
  p->offs.assign(offs, offs + n);
  p->lens.assign(lens, lens + n);
  p->H = H; p->W = W; p->resize = resize;
  p->rand_crop = rand_crop; p->rand_mirror = rand_mirror;
  p->label_width = std::max(1, label_width);
  p->capacity = std::max(capacity, 2 * threads);
  std::memcpy(p->mean, mean, 12);
  std::memcpy(p->stdv, stdv, 12);
  p->seed = seed;
  int nt = std::max(1, threads);
  for (int i = 0; i < nt; ++i)
    p->workers.emplace_back([p] { p->worker(); });
  return p;
}

// Install a new epoch order (record indices) and reset sequencing;
// `seed` reseeds the augmentation RNG so crops/mirrors vary per epoch.
void mxio_pipe_schedule(void* h, const int64_t* order, int64_t n,
                        uint64_t seed) {
  Pipe* p = static_cast<Pipe*>(h);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->tasks.clear();
    p->done.clear();
    p->epoch++;
    p->epoch_len = n;
    p->next_out = 0;
    p->seed = seed;
    for (int64_t i = 0; i < n; ++i)
      p->tasks.push_back(Task{p->epoch, i, order[i], seed});
  }
  p->cv_task.notify_all();
}

// Fill one batch (NCHW float data + labels + ok flags).  Returns the
// number of samples filled (< batch at end of epoch).
int64_t mxio_pipe_next(void* h, int64_t batch, float* data_out,
                       float* label_out, uint8_t* ok_out) {
  Pipe* p = static_cast<Pipe*>(h);
  const size_t isz = static_cast<size_t>(3) * p->H * p->W;
  int64_t filled = 0;
  for (; filled < batch; ++filled) {
    std::unique_lock<std::mutex> lk(p->mu);
    int64_t want = p->next_out;
    if (want >= p->epoch_len) break;
    p->cv_done.wait(lk, [&] { return p->done.count(want) > 0; });
    auto it = p->done.find(want);
    Result r = std::move(it->second);
    p->done.erase(it);
    p->next_out++;
    lk.unlock();
    p->cv_task.notify_all();   // capacity freed
    std::memcpy(data_out + filled * isz, r.data.data(), isz * 4);
    std::memcpy(label_out + filled * p->label_width, r.label.data(),
                p->label_width * 4);
    ok_out[filled] = r.ok;
  }
  return filled;
}

void mxio_pipe_close(void* h) {
  Pipe* p = static_cast<Pipe*>(h);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->stop = true;
  }
  p->cv_task.notify_all();
  for (auto& t : p->workers) t.join();
  close(p->fd);
  delete p;
}

}  // extern "C"
